"""Worker HTTP client: the remote-task / exchange-client consumer side.

Reference surface: HttpRemoteTaskWithEventLoop.java:157 (sendUpdate:981
POSTing TaskUpdateRequests) and ExchangeClient.java:255 / PageBufferClient
(token/ack SerializedPage pull) -- collapsed into one small synchronous
client suitable for tests and cross-slice fetches.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .. import failpoints
from .. import types as T
from ..plan import nodes as N
from ..serde import PageCodec, deserialize_page
from ..utils.backoff import Backoff

__all__ = ["WorkerClient"]


class _HttpStatusError(urllib.error.HTTPError):
    """Status-code error with urllib's .code surface, so existing
    callers (410-token checks, 401 auth tests) keep one catch type."""

    def __init__(self, status: int, data: bytes, path: str):
        import io
        super().__init__(path, status,
                         data.decode("utf-8", "replace")[:500], None,
                         io.BytesIO(data))


class WorkerClient:
    """Persistent-connection client: one keep-alive HTTP/1.1 connection
    per (client, thread), reused across the token/ack pull loop and task
    polls (the reference's pooled PageBufferClient/Netty channel; the
    round-4 per-request urllib connections cost a TCP handshake per
    page). Stale keep-alive sockets (server-side idle close) retry once
    on a fresh connection."""

    def __init__(self, base_url: str, timeout: float = 30.0,
                 shared_secret: Optional[str] = None):
        from .auth import make_authenticator
        self.base = base_url.rstrip("/")
        self.timeout = timeout
        self._secret = shared_secret  # re-target (moved pages) clients
        self._auth = make_authenticator(shared_secret, "client")
        u = urllib.parse.urlsplit(self.base)
        self._scheme = u.scheme or "http"
        self._host, self._port = u.hostname, u.port
        self._prefix = u.path.rstrip("/")
        self._local = threading.local()

    def _connect(self) -> http.client.HTTPConnection:
        if self._scheme == "https":
            from .tls import client_ssl_context
            return http.client.HTTPSConnection(
                self._host, self._port, timeout=self.timeout,
                context=client_ssl_context())
        return http.client.HTTPConnection(self._host, self._port,
                                          timeout=self.timeout)

    def _request(self, method: str, path: str, body: Optional[bytes] = None):
        from .auth import bearer_headers
        from .tracing import TRACE_HEADER, current_context
        headers = dict(bearer_headers(self._auth))
        if body is not None:
            headers["Content-Type"] = "application/json"
        ctx = current_context()
        if ctx is not None:
            # every hop this thread makes on a query's behalf (task
            # create/status, exchange-buffer fetch) carries the trace
            headers[TRACE_HEADER] = ctx.header()
        last_err = None
        for attempt in (0, 1):
            conn = getattr(self._local, "conn", None)
            if conn is None:
                conn = self._connect()
                self._local.conn = conn
            try:
                if failpoints.ARMED:
                    # drop_conn here is an injected stale keep-alive
                    # socket: a ConnectionError the retry below handles
                    failpoints.hit("client.request")
                conn.request(method, self._prefix + path, body=body,
                             headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                if resp.status >= 400:
                    self._raise_http(resp.status, data, path)
                return data, dict(resp.getheaders())
            except (http.client.HTTPException, ConnectionError,
                    BrokenPipeError, TimeoutError) as e:
                if isinstance(e, _HttpStatusError):
                    raise
                self._local.conn = None
                try:
                    conn.close()
                except Exception as ce:  # noqa: BLE001 - already
                    # failing; `ce` not `e`: an inner `as e` would
                    # delete the outer binding on handler exit
                    from .metrics import record_suppressed
                    record_suppressed("worker_client", "conn_close", ce)
                last_err = e
                if attempt == 1:
                    raise
                # stale keep-alive retry: on the flight-recorder
                # timeline so a post-mortem sees flaky transport
                from .flight_recorder import record_event
                record_event("http_retry", path=path,
                             error=f"{type(e).__name__}: {e}")
                # brief seeded backoff before the fresh-connection
                # retry: a reset usually means the peer is busy or
                # mid-restart, and an instant retry piles on
                Backoff(base_s=0.02, cap_s=0.25, seed=path).sleep()
        raise last_err  # unreachable

    @staticmethod
    def _raise_http(status: int, data: bytes, path: str):
        raise _HttpStatusError(status, data, path)

    def info(self) -> dict:
        data, _ = self._request("GET", "/v1/info")
        return json.loads(data)

    def profile(self) -> dict:
        """The worker's per-kernel profile slice (GET /v1/profile) --
        authenticated/TLS'd like every other internal hop, so the
        coordinator's cluster merge works on secured clusters too."""
        data, _ = self._request("GET", "/v1/profile")
        return json.loads(data)

    def history(self) -> dict:
        """The worker's completed-query history slice (GET /v1/history),
        pulled over the same authenticated transport as profile() so
        the statement tier's cluster merge works on secured clusters."""
        data, _ = self._request("GET", "/v1/history")
        return json.loads(data)

    def datapath(self) -> dict:
        """The worker's per-hop data-path slice (GET /v1/datapath),
        pulled over the same authenticated transport as profile() so
        the statement tier's cluster merge works on secured clusters."""
        data, _ = self._request("GET", "/v1/datapath")
        return json.loads(data)

    def accuracy(self) -> dict:
        """The worker's estimate-accuracy slice (GET /v1/accuracy),
        pulled over the same authenticated transport as profile() so
        the statement tier's cluster merge works on secured clusters."""
        data, _ = self._request("GET", "/v1/accuracy")
        return json.loads(data)

    def timeline(self) -> dict:
        """The worker's execution-timeline slice (GET /v1/timeline),
        pulled over the same authenticated transport as profile() so
        the statement tier's cluster merge works on secured clusters."""
        data, _ = self._request("GET", "/v1/timeline")
        return json.loads(data)

    def status(self) -> dict:
        """The worker's enriched NodeStatus (GET /v1/status): liveness,
        uptime, version, running tasks, memory-pool occupancy -- the
        per-worker row of the statement tier's /v1/cluster overview."""
        data, _ = self._request("GET", "/v1/status")
        return json.loads(data)

    def submit(self, task_id: str, plan: N.PlanNode, sf: float = 0.01,
               session: Optional[dict] = None) -> dict:
        return self.submit_body(task_id, {"plan": N.to_json(plan), "sf": sf,
                                          "session": session or {}})

    def submit_body(self, task_id: str, body: dict) -> dict:
        """Raw TaskUpdateRequest submission (scanRanges / remoteSources
        and other fields pass through verbatim)."""
        data, _ = self._request("POST", f"/v1/task/{task_id}",
                                json.dumps(body).encode())
        return json.loads(data)

    def migrate(self, task_id: str, doc: dict) -> dict:
        """Offer a finished task's buffered pages for adoption
        (graceful-drain migration hop; POST /v1/task/{id}/migrate)."""
        data, _ = self._request("POST", f"/v1/task/{task_id}/migrate",
                                json.dumps(doc).encode())
        return json.loads(data)

    def drain(self, migrate_to: Optional[str] = None,
              timeout_ms: Optional[float] = None) -> dict:
        """Start the worker's graceful drain (POST /v1/worker/drain);
        returns the drain-status document."""
        body = {}
        if migrate_to:
            body["migrateTo"] = migrate_to
        if timeout_ms is not None:
            body["timeoutMs"] = float(timeout_ms)
        data, _ = self._request("POST", "/v1/worker/drain",
                                json.dumps(body).encode())
        return json.loads(data)

    def drain_status(self) -> dict:
        data, _ = self._request("GET", "/v1/worker/drain")
        return json.loads(data)

    def task_info(self, task_id: str) -> dict:
        data, _ = self._request("GET", f"/v1/task/{task_id}")
        return json.loads(data)

    def wait(self, task_id: str, timeout: float = 60.0) -> dict:
        deadline = time.time() + timeout
        info = None
        while time.time() < deadline:
            info = self.task_info(task_id)
            self._note_progress(task_id, info)
            if info["state"] in ("FINISHED", "FAILED", "ABORTED"):
                return info
            time.sleep(0.05)
        state = info["state"] if info else "<never polled>"
        raise TimeoutError(f"task {task_id} still {state}")

    def _note_progress(self, task_id: str, info: dict) -> None:
        """Fold the progress heartbeat riding a TaskInfo poll into the
        local registry (exec/progress.py), tagged with the ambient
        trace id -- how the coordinator/statement process learns what
        every remote task is doing mid-flight. A terminal TaskInfo
        state finishes the entry even when the shipped snapshot lags
        behind it (the worker flips the task terminal a beat before
        its own finish_task runs): wait() stops polling on the
        terminal state, so this poll is the last chance to close the
        entry. Never raises."""
        from .tracing import current_context
        if not isinstance(info, dict):
            return
        from ..exec.progress import finish_task, note_remote
        doc = info.get("progress")
        if doc:
            ctx = current_context()
            note_remote(task_id, doc, worker=self.base,
                        query=ctx.trace_id if ctx is not None else None)
        state = info.get("state")
        if state in ("FINISHED", "FAILED", "ABORTED"):
            finish_task(task_id, state)

    def fetch_results(self, task_id: str, types: Sequence[T.Type],
                      codec: PageCodec = PageCodec(), buffer_id: int = 0,
                      ack: bool = True
                      ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Token/ack pull loop until the buffer reports complete; returns
        concatenated (values, nulls) per column. Raises on deadline or on
        HTTP 410 (pages acked away by a prior consumer attempt). A
        drained-away task (``X-Presto-Task-Moved`` header) re-targets
        the adopting peer and resumes the SAME absolute token, so the
        page stream replays exactly once across the migration."""
        token = 0
        pages = []
        target = self  # re-targeted when the task's pages migrated
        moves = 0
        last_move = None  # last followed move target (normalized url)
        peer_misses = 0  # consecutive 404s after following a move
        deadline = time.time() + self.timeout
        while True:
            if time.time() > deadline:
                raise TimeoutError(
                    f"results of {task_id}/{buffer_id} not complete after "
                    f"{self.timeout}s")
            try:
                data, headers = target._request(
                    "GET",
                    f"/v1/task/{task_id}/results/{buffer_id}/{token}")
            except urllib.error.HTTPError as e:
                if e.code == 404 and target is not self:
                    # the adopt POST may still be in flight on the
                    # peer -- or it FAILED and the origin rolled its
                    # moved_to flip back and still serves the pages:
                    # retry the peer briefly, then fall back to the
                    # origin (which either serves directly or re-issues
                    # the move once the adopt finally landed)
                    peer_misses += 1
                    if peer_misses >= 10:
                        peer_misses = 0
                        target = self
                        continue
                    time.sleep(0.05)
                    continue
                raise
            peer_misses = 0
            moved = headers.get("X-Presto-Task-Moved")
            if moved:
                # count only moves to a NEW target toward the loop cap:
                # re-following the SAME pending migration after an
                # origin fallback is the slow-adopt wait (bounded by
                # the deadline), not a redirect chain
                if moved.rstrip("/") != last_move:
                    moves += 1
                    if moves >= 8:
                        raise RuntimeError(
                            f"task {task_id} pages moved too many "
                            f"times (migration loop?)")
                    last_move = moved.rstrip("/")
                target = WorkerClient(moved, self.timeout,
                                      shared_secret=self._secret)
                continue
            complete = headers.get("X-Presto-Buffer-Complete") == "true"
            next_token = int(headers.get("X-Presto-Page-Next-Token", token))
            if data:
                pages.append(deserialize_page(data, types, codec))
                if ack:
                    target._request(
                        "GET",
                        f"/v1/task/{task_id}/results/{buffer_id}/{next_token}/acknowledge")
                token = next_token
            elif complete:
                break
            else:
                time.sleep(0.02)
        if not pages:
            return [(np.array([]), np.array([], dtype=bool)) for _ in types]
        out = []
        for c in range(len(types)):
            vals = np.concatenate([p[c][0] for p in pages])
            nulls = np.concatenate([p[c][1] for p in pages])
            out.append((vals, nulls))
        return out

    def abort(self, task_id: str) -> dict:
        data, _ = self._request("DELETE", f"/v1/task/{task_id}")
        return json.loads(data)


def pull_worker_docs(worker_urls, timeout: float, fetch,
                     component: str, site: str = "cluster_pull",
                     parallel: bool = False, placeholder=None):
    """The one best-effort cluster pull the merged surfaces
    (/v1/profile, /v1/history, /v1/cluster) share: fetch one document
    per reachable worker through an authenticated WorkerClient,
    skip-and-count the unreachable ones (never an error).
    ``fetch(client) -> dict``; returns (docs, workers_pulled) with
    docs in input-URL order; workers_pulled counts REACHABLE workers
    only. ``parallel`` fans the pulls out on a small thread pool --
    the live /v1/cluster probe uses it so ONE dead worker costs one
    timeout per frame, not one per dead worker. ``placeholder(url) ->
    dict`` keeps unreachable workers IN the doc list (the fleet view's
    DEAD rows) instead of silently dropping them."""
    from .metrics import record_suppressed

    def pull(url):
        try:
            return fetch(WorkerClient(str(url), timeout))
        except Exception as e:  # noqa: BLE001 - a dead worker must not
            # fail the cluster view; the gap is counted on /v1/metrics
            record_suppressed(component, site, e)
            return None
    urls = list(worker_urls or ())
    if parallel and len(urls) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=min(8, len(urls))) as pool:
            results = list(pool.map(pull, urls))
    else:
        results = [pull(u) for u in urls]
    alive = sum(1 for d in results if d is not None)
    if placeholder is not None:
        docs = [d if d is not None else placeholder(str(u))
                for u, d in zip(urls, results)]
    else:
        docs = [d for d in results if d is not None]
    return docs, alive
