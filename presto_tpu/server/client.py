"""Worker HTTP client: the remote-task / exchange-client consumer side.

Reference surface: HttpRemoteTaskWithEventLoop.java:157 (sendUpdate:981
POSTing TaskUpdateRequests) and ExchangeClient.java:255 / PageBufferClient
(token/ack SerializedPage pull) -- collapsed into one small synchronous
client suitable for tests and cross-slice fetches.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..plan import nodes as N
from ..serde import PageCodec, deserialize_page

__all__ = ["WorkerClient"]


class WorkerClient:
    def __init__(self, base_url: str, timeout: float = 30.0,
                 shared_secret: Optional[str] = None):
        from .auth import make_authenticator
        self.base = base_url.rstrip("/")
        self.timeout = timeout
        self._auth = make_authenticator(shared_secret, "client")

    def _request(self, method: str, path: str, body: Optional[bytes] = None):
        from .auth import bearer_headers
        req = urllib.request.Request(self.base + path, data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        for k, v in bearer_headers(self._auth).items():
            req.add_header(k, v)
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read(), dict(resp.headers)

    def info(self) -> dict:
        data, _ = self._request("GET", "/v1/info")
        return json.loads(data)

    def submit(self, task_id: str, plan: N.PlanNode, sf: float = 0.01,
               session: Optional[dict] = None) -> dict:
        return self.submit_body(task_id, {"plan": N.to_json(plan), "sf": sf,
                                          "session": session or {}})

    def submit_body(self, task_id: str, body: dict) -> dict:
        """Raw TaskUpdateRequest submission (scanRanges / remoteSources
        and other fields pass through verbatim)."""
        data, _ = self._request("POST", f"/v1/task/{task_id}",
                                json.dumps(body).encode())
        return json.loads(data)

    def task_info(self, task_id: str) -> dict:
        data, _ = self._request("GET", f"/v1/task/{task_id}")
        return json.loads(data)

    def wait(self, task_id: str, timeout: float = 60.0) -> dict:
        deadline = time.time() + timeout
        info = None
        while time.time() < deadline:
            info = self.task_info(task_id)
            if info["state"] in ("FINISHED", "FAILED", "ABORTED"):
                return info
            time.sleep(0.05)
        state = info["state"] if info else "<never polled>"
        raise TimeoutError(f"task {task_id} still {state}")

    def fetch_results(self, task_id: str, types: Sequence[T.Type],
                      codec: PageCodec = PageCodec(), buffer_id: int = 0,
                      ack: bool = True
                      ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Token/ack pull loop until the buffer reports complete; returns
        concatenated (values, nulls) per column. Raises on deadline or on
        HTTP 410 (pages acked away by a prior consumer attempt)."""
        token = 0
        pages = []
        deadline = time.time() + self.timeout
        while True:
            if time.time() > deadline:
                raise TimeoutError(
                    f"results of {task_id}/{buffer_id} not complete after "
                    f"{self.timeout}s")
            data, headers = self._request(
                "GET", f"/v1/task/{task_id}/results/{buffer_id}/{token}")
            complete = headers.get("X-Presto-Buffer-Complete") == "true"
            next_token = int(headers.get("X-Presto-Page-Next-Token", token))
            if data:
                pages.append(deserialize_page(data, types, codec))
                if ack:
                    self._request(
                        "GET",
                        f"/v1/task/{task_id}/results/{buffer_id}/{next_token}/acknowledge")
                token = next_token
            elif complete:
                break
            else:
                time.sleep(0.02)
        if not pages:
            return [(np.array([]), np.array([], dtype=bool)) for _ in types]
        out = []
        for c in range(len(types)):
            vals = np.concatenate([p[c][0] for p in pages])
            nulls = np.concatenate([p[c][1] for p in pages])
            out.append((vals, nulls))
        return out

    def abort(self, task_id: str) -> dict:
        data, _ = self._request("DELETE", f"/v1/task/{task_id}")
        return json.loads(data)
