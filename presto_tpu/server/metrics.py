"""Prometheus text-format metrics: the one emitter both tiers share.

Reference surface: the native worker's PrometheusStatsReporter
(presto_cpp/main/PrometheusStatsReporter.cpp) and PrestoServer's
registerHttpEndpoints wiring a scrapeable endpoint; on the Java side
the JMX connector exports the same counters. Both the coordinator
(statement server) and the worker serve ``GET /v1/metrics`` rendering
through this module, so scrape format and naming conventions cannot
drift between tiers.

Format is the Prometheus exposition text format v0.0.4: per family a
``# HELP`` line, a ``# TYPE`` line (counter | gauge | histogram), then
one sample per label set. Histogram families render the cumulative
``_bucket{le=...}`` ladder (``+Inf`` == ``_count``) plus ``_sum`` /
``_count``; buckets carrying an exemplar append the OpenMetrics-style
``# {trace_id="..."} <value>`` suffix, which links a latency bucket
straight to ``GET /v1/trace/{traceId}``. Labels are rendered sorted
for deterministic scrapes (scripts/scrape_metrics.py diffs two
scrapes textually-parsed).

Latency distributions live in a process-wide histogram registry
(:func:`observe_histogram`): the hot seams -- query end-to-end and
per-state wall (statement), dispatcher queue-wait, per-stage micros
(runner), exchange fetch (http_exchange), page serde (serde/pages),
task lifetime (worker) -- observe into named histograms with FIXED
log-spaced buckets, so per-process distributions merge associatively
and a scrape shape is stable from the first request on (declared
families render zeros before any observation).
"""

from __future__ import annotations

import bisect
import logging
import threading
import time as _time
from typing import Dict, List, Optional, Tuple, Union

from ..utils.locks import OrderedLock

__all__ = ["MetricFamily", "Histogram", "DEFAULT_BUCKETS",
           "SIZE_BUCKETS", "Q_ERROR_BUCKETS", "datapath_families",
           "accuracy_families",
           "observe_histogram", "get_histogram", "histogram_families",
           "reset_histograms",
           "render_prometheus", "parse_prometheus",
           "negotiate_exposition", "CONTENT_TYPE_OPENMETRICS",
           "plan_cache_families", "narrowing_families",
           "batching_families", "uptime_family",
           "record_suppressed", "suppressed_error_families",
           "suppressed_error_totals", "tracing_families",
           "flight_recorder_families", "kernel_audit_families",
           "donation_families",
           "failpoint_families", "query_history_families",
           "live_introspection_families", "fleet_families",
           "lock_families", "CONTENT_TYPE"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
# exemplars are legal only in the OpenMetrics exposition (the classic
# 0.0.4 text parser rejects a `# {...}` suffix after the value): the
# /v1/metrics handlers negotiate via the Accept header and render
# exemplars only under this content type
CONTENT_TYPE_OPENMETRICS = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"

_LabelSample = Tuple[Dict[str, str], Union[int, float]]

# The one bucket scheme every latency histogram shares (seconds,
# log-spaced 1-2.5-5 ladder from 100us to 100s). FIXED buckets are what
# make Histogram.merge associative+commutative across workers without
# negotiation -- the same property QueryStats.merge relies on.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0)

# The bytes-oriented ladder beside the time ladder: 1 KiB -> 4 GiB,
# log-spaced (powers of 4), so page/batch/payload SIZE distributions
# have somewhere to land -- a page-size histogram forced onto the
# seconds ladder would put every sample in +Inf. Fixed bounds keep
# Histogram.merge elementwise-add associative+commutative across
# workers, same law, same exemplar contract as the time ladder.
SIZE_BUCKETS: Tuple[float, ...] = tuple(
    float(1024 * 4 ** i) for i in range(12))  # 1KiB .. 4GiB

# The q-error ladder beside the two above: estimate accuracy is a
# RATIO >= 1.0 (exec/accuracy.py, max(est/act, act/est)), log-spaced in
# powers of 2 from "exact" to "off by ~1000x" -- a misestimate
# distribution forced onto the seconds ladder would crowd everything
# under 2.5. Fixed bounds keep Histogram.merge lawful across processes.
Q_ERROR_BUCKETS: Tuple[float, ...] = tuple(
    float(2 ** i) for i in range(11))  # 1x .. 1024x


class Histogram:
    """Mergeable latency distribution over fixed bucket bounds.

    The merge law mirrors ``QueryStats.merge``: counts/sum add
    elementwise, exemplars keep the larger observation -- associative,
    commutative, with the empty histogram as identity -- so per-worker
    histograms fold into a cluster view in any order. ``observe`` is
    thread-safe (one lock per histogram; request-handler, task and
    engine threads all observe concurrently).

    Exemplars: per bucket, the (trace_id, value, tsUs) of the
    MAX-latency observation that landed in that bucket (only kept when
    the observer supplied a trace id), so the worst sample of every
    latency band links to its distributed trace.
    """

    _GUARDED_BY = {"_lock": ("counts", "sum", "count", "exemplars")}

    def __init__(self, buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.buckets = tuple(float(b) for b in buckets)
        assert list(self.buckets) == sorted(set(self.buckets)), \
            "bucket bounds must be strictly ascending"
        # counts[i] = observations <= buckets[i]'s bound and > the
        # previous bound (per-bucket, NOT cumulative; render cumulates);
        # counts[-1] is the +Inf overflow bucket
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0
        # per-bucket (trace_id, value, ts_us) of the max observation
        self.exemplars: List[Optional[Tuple[str, float, int]]] = \
            [None] * (len(self.buckets) + 1)
        self._lock = OrderedLock("metrics.Histogram._lock")

    def observe(self, value: float,
                trace_id: Optional[str] = None) -> None:
        v = float(value)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1
            if trace_id:
                ex = self.exemplars[i]
                if ex is None or v >= ex[1]:
                    self.exemplars[i] = (str(trace_id), v,
                                         int(_time.time() * 1e6))

    def merge(self, other: "Histogram") -> "Histogram":
        if self.buckets != other.buckets:
            raise ValueError("cannot merge histograms with different "
                             f"bucket schemes: {len(self.buckets)} vs "
                             f"{len(other.buckets)} bounds")
        out = Histogram(self.buckets)
        a, b = self.snapshot(), other.snapshot()
        with out._lock:  # fresh object, but the write barrier is uniform
            out.counts = [x + y for x, y in zip(a["counts"], b["counts"])]
            out.sum = a["sum"] + b["sum"]
            out.count = a["count"] + b["count"]
            out.exemplars = [
                _max_exemplar(x, y)
                for x, y in zip(a["exemplars"], b["exemplars"])]
        return out

    def snapshot(self) -> dict:
        """Consistent copy (render/merge never see a torn update)."""
        with self._lock:
            return {"buckets": self.buckets,
                    "counts": list(self.counts),
                    "sum": self.sum, "count": self.count,
                    "exemplars": list(self.exemplars)}

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate (the scrape-side p50/
        p95/p99 arithmetic, shared with scripts/scrape_metrics.py)."""
        return quantile_from_buckets(self.buckets,
                                     self.snapshot()["counts"], q)

    def to_json(self) -> dict:
        snap = self.snapshot()
        return {"buckets": list(snap["buckets"]),
                "counts": snap["counts"],
                "sum": snap["sum"], "count": snap["count"],
                "exemplars": [list(e) if e else None
                              for e in snap["exemplars"]]}

    @classmethod
    def from_json(cls, doc: dict) -> "Histogram":
        h = cls(tuple(doc["buckets"]))
        ex = doc.get("exemplars") or [None] * (len(h.buckets) + 1)
        with h._lock:  # fresh object, but the write barrier is uniform
            h.counts = [int(c) for c in doc["counts"]]
            h.sum = float(doc["sum"])
            h.count = int(doc["count"])
            h.exemplars = [tuple(e) if e else None for e in ex]
        return h


def _max_exemplar(a, b):
    """Larger observation wins; ties break by timestamp then trace id,
    so the merge stays commutative (order of folding cannot pick a
    different exemplar)."""
    if a is None:
        return b
    if b is None:
        return a
    return a if (a[1], a[2], a[0]) >= (b[1], b[2], b[0]) else b


def quantile_from_buckets(bounds, counts, q: float) -> float:
    """Estimate the q-quantile of a (non-cumulative) bucket-count
    vector by linear interpolation within the bucket containing rank
    q*count; the +Inf bucket reports the last finite bound."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = q * total
    acc = 0.0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if acc + c >= rank:
            hi = bounds[i] if i < len(bounds) else bounds[-1]
            lo = bounds[i - 1] if 0 < i <= len(bounds) else 0.0
            frac = (rank - acc) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        acc += c
    return float(bounds[-1])


class MetricFamily:
    """One metric family: name, type, help, and samples (optionally
    labelled). Histogram families carry Histogram snapshots instead of
    scalar samples and render the full cumulative-bucket ladder."""

    def __init__(self, name: str, mtype: str, help_: str):
        assert mtype in ("counter", "gauge", "histogram"), mtype
        self.name = name
        self.mtype = mtype
        self.help = help_
        self.samples: List[_LabelSample] = []
        self.histograms: List[Tuple[Dict[str, str], dict]] = []

    def add(self, value: Union[int, float],
            labels: Optional[Dict[str, str]] = None) -> "MetricFamily":
        self.samples.append((dict(labels or {}), value))
        return self

    def add_histogram(self, hist: "Histogram",
                      labels: Optional[Dict[str, str]] = None
                      ) -> "MetricFamily":
        self.histograms.append((dict(labels or {}), hist.snapshot()))
        return self

    def _label_str(self, labels: Dict[str, str]) -> str:
        return ",".join(f'{k}="{_escape(v)}"'
                        for k, v in sorted(labels.items()))

    def render(self, exemplars: bool = True) -> List[str]:
        """`exemplars=False` renders strictly classic-0.0.4 text (the
        default /v1/metrics scrape); True appends the OpenMetrics
        exemplar suffix on histogram buckets that carry one."""
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.mtype}"]
        for labels, value in self.samples:
            if labels:
                lines.append(
                    f"{self.name}{{{self._label_str(labels)}}} "
                    f"{_num(value)}")
            else:
                lines.append(f"{self.name} {_num(value)}")
        for labels, snap in self.histograms:
            lines.extend(self._render_histogram(labels, snap,
                                                exemplars))
        return lines

    def _render_histogram(self, labels: Dict[str, str], snap: dict,
                          exemplars: bool) -> List[str]:
        lines: List[str] = []
        cum = 0
        for i, bound in enumerate(snap["buckets"]):
            cum += snap["counts"][i]
            lab = self._label_str({**labels, "le": _num(float(bound))})
            line = f"{self.name}_bucket{{{lab}}} {cum}"
            ex = snap["exemplars"][i]
            if exemplars and ex is not None:
                # OpenMetrics exemplar: the max-latency observation of
                # this bucket, linking to GET /v1/trace/{trace_id}
                line += (f' # {{trace_id="{_escape(ex[0])}"}} '
                         f"{_num(float(ex[1]))}")
            lines.append(line)
        cum += snap["counts"][-1]
        lab = self._label_str({**labels, "le": "+Inf"})
        line = f"{self.name}_bucket{{{lab}}} {cum}"
        ex = snap["exemplars"][-1]
        if exemplars and ex is not None:
            line += (f' # {{trace_id="{_escape(ex[0])}"}} '
                     f"{_num(float(ex[1]))}")
        lines.append(line)
        tail = f"{{{self._label_str(labels)}}}" if labels else ""
        lines.append(f"{self.name}_sum{tail} {_num(snap['sum'])}")
        lines.append(f"{self.name}_count{tail} {snap['count']}")
        return lines


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def _num(v: Union[int, float]) -> str:
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    return repr(round(float(v), 6))


# -- process histogram registry -----------------------------------------
#
# Named latency histograms observed from the hot seams. Declared
# families render on EVERY scrape (zeros included) so both tiers'
# /v1/metrics carry a stable histogram shape from the first request on;
# undeclared names observed at runtime export too.

_HIST_LOCK = OrderedLock("metrics._HIST_LOCK")
_HISTOGRAMS: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Histogram] = {}

# name -> (help text, preset label sets rendered even before any
# observation). The label values are the closed vocabularies of each
# seam, so a dashboard's first scrape already shows every series.
_DECLARED_HISTOGRAMS: Dict[str, Tuple[str, Tuple[Dict[str, str], ...]]] = {
    "presto_tpu_query_latency_seconds": (
        "end-to-end statement latency (queued -> terminal)", ({},)),
    "presto_tpu_query_state_seconds": (
        "per-state statement wall time (QueryStateMachine transitions)",
        tuple({"state": s} for s in
              ("QUEUED", "PLANNING", "RUNNING", "FINISHING"))),
    "presto_tpu_dispatch_queue_wait_seconds": (
        "admission wait in the dispatcher's resource-group queue "
        "(cluster gate + local slot), labeled by resource group so "
        "per-latency-class p99s are attributable",
        tuple({"group": g} for g in
              ("global", "global.interactive", "global.dashboard",
               "global.batch"))),
    "presto_tpu_batch_occupancy_queries": (
        "queries served per batched dispatch (exec/batching.py "
        "formation outcomes; solo serial dispatches do not observe)",
        ({},)),
    "presto_tpu_stage_seconds": (
        "per-query host-visible stage wall (exec/stats.py stages)",
        tuple({"stage": s} for s in
              ("staging", "compile", "execute", "exchange", "fetch"))),
    "presto_tpu_exchange_fetch_seconds": (
        "cross-worker exchange pull+decode (http_exchange."
        "fetch_remote_batch)", ({},)),
    "presto_tpu_page_serde_seconds": (
        "SerializedPage codec work per page", tuple(
            {"op": s} for s in ("serialize", "deserialize"))),
    "presto_tpu_task_seconds": (
        "worker task lifetime (create -> terminal)", ({},)),
    # the data-path waterfall's per-hop payload-size distribution
    # (exec/datapath.py record_hop): SIZE_BUCKETS ladder, one series
    # per catalog hop. The label values are spelled literally (like
    # every closed vocabulary above); tests pin them to datapath.HOPS.
    "presto_tpu_datapath_bytes": (
        "per-hop data-path payload size (bytes ladder; "
        "exec/datapath.py hop catalog)",
        tuple({"hop": h} for h in
              ("connector_read", "decode", "narrow_cast", "device_put",
               "kernel", "exchange_serialize", "exchange_fetch",
               "client_drain"))),
    # the estimate-accuracy observatory's q-error distribution
    # (exec/accuracy.py finalize_query): Q_ERROR_BUCKETS ladder, one
    # series per unit of the closed catalog. Label values spelled
    # literally (like every closed vocabulary above); tests pin them
    # to accuracy.UNITS.
    "presto_tpu_q_error": (
        "per-plan-node estimate q-error max(est/act, act/est) "
        "(ratio ladder; exec/accuracy.py unit catalog)",
        tuple({"unit": u} for u in ("rows", "bytes"))),
}

# histogram families whose observations are NOT seconds use their own
# fixed ladder (one scheme per family name: merge stays lawful because
# every instance of a name shares the same bounds)
_BUCKET_SCHEMES: Dict[str, Tuple[float, ...]] = {
    "presto_tpu_datapath_bytes": SIZE_BUCKETS,
    "presto_tpu_q_error": Q_ERROR_BUCKETS,
}


def _hist_key(name: str, labels: Optional[Dict[str, str]]
              ) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    return (name, tuple(sorted((labels or {}).items())))


def get_histogram(name: str, labels: Optional[Dict[str, str]] = None
                  ) -> Histogram:
    """The named histogram (created on first use; fixed buckets per
    family name -- the time ladder unless _BUCKET_SCHEMES declares a
    size ladder -- so every instance merges with every other)."""
    key = _hist_key(name, labels)
    with _HIST_LOCK:
        h = _HISTOGRAMS.get(key)
        if h is None:
            h = _HISTOGRAMS[key] = Histogram(
                _BUCKET_SCHEMES.get(name, DEFAULT_BUCKETS))
        return h


def observe_histogram(name: str, value: float,
                      labels: Optional[Dict[str, str]] = None,
                      trace_id: Optional[str] = None) -> None:
    """Observe one latency sample into the process registry. Never
    raises: this sits on request/task hot paths."""
    try:
        get_histogram(name, labels).observe(value, trace_id=trace_id)
    except Exception as e:  # noqa: BLE001 - telemetry must never fail
        # the request that carried it; a broken registry is counted
        record_suppressed("metrics", "observe_histogram", e)


def histogram_families() -> List[MetricFamily]:
    """Every declared + observed histogram family (shared by both
    tiers' /v1/metrics, like the counter builders above)."""
    with _HIST_LOCK:
        live = dict(_HISTOGRAMS)
    fams: List[MetricFamily] = []
    names = list(_DECLARED_HISTOGRAMS) + sorted(
        {n for n, _ in live} - set(_DECLARED_HISTOGRAMS))
    for name in names:
        help_, presets = _DECLARED_HISTOGRAMS.get(
            name, ("runtime-observed latency histogram", ({},)))
        fam = MetricFamily(name, "histogram", help_)
        keys = {_hist_key(name, p)[1] for p in presets}
        keys |= {lk for n, lk in live if n == name}
        for lk in sorted(keys):
            labels = dict(lk)
            fam.add_histogram(
                live.get((name, lk)) or
                Histogram(_BUCKET_SCHEMES.get(name, DEFAULT_BUCKETS)),
                labels)
        fams.append(fam)
    return fams


def reset_histograms() -> None:
    """Drop every observed histogram (tests isolate scrape state)."""
    with _HIST_LOCK:
        _HISTOGRAMS.clear()


def plan_cache_families() -> List[MetricFamily]:
    """The compiled-plan cache families both tiers export -- ONE
    builder so the names cannot drift between coordinator and worker."""
    from ..exec.plan_cache import cache_stats
    st = cache_stats()
    return [
        MetricFamily("presto_tpu_plan_cache_entries", "gauge",
                     "compiled-plan cache entries").add(st["entries"]),
        MetricFamily("presto_tpu_plan_cache_hits_total", "counter",
                     "compiled-plan cache hits").add(st["hits"]),
        MetricFamily("presto_tpu_plan_cache_misses_total", "counter",
                     "compiled-plan cache misses").add(st["misses"]),
    ]


def batching_families() -> List[MetricFamily]:
    """Concurrent-query batching totals (exec/batching.py), exported
    by BOTH tiers with a stable zero shape: dispatch amortization
    (batches vs queries served), collapse reasons, and the live
    occupancy gauge /v1/cluster mirrors."""
    from ..exec.batching import COLLAPSE_REASONS, batching_totals
    t = batching_totals()
    fam_c = MetricFamily("presto_tpu_batch_collapses_total", "counter",
                         "formed batches collapsed back to serial "
                         "dispatch, by reason")
    for r in COLLAPSE_REASONS:
        fam_c.add(t["collapses"].get(r, 0), {"reason": r})
    return [
        MetricFamily("presto_tpu_batch_dispatches_total", "counter",
                     "batched dispatches executed (one vmapped program "
                     "per batch)").add(t["batches"]),
        MetricFamily("presto_tpu_batched_queries_total", "counter",
                     "queries served by a batched dispatch").add(
                         t["batched_queries"]),
        MetricFamily("presto_tpu_batch_solo_dispatches_total", "counter",
                     "batch-of-1 dispatches riding an already-warm "
                     "template program (no co-batching, no fresh "
                     "compile)").add(t.get("solo_dispatches", 0)),
        fam_c,
        MetricFamily("presto_tpu_batch_occupancy", "gauge",
                     "queries per dispatch of the last formed "
                     "batch").add(t["last_batch_size"]),
    ]


def datapath_families() -> List[MetricFamily]:
    """Data-path waterfall lifetime totals (exec/datapath.py), exported
    by BOTH tiers with a stable zero shape: per-hop bytes moved and
    wall burned -- the counters whose scrape-window ratio IS the hop's
    achieved B/s, beside the SIZE_BUCKETS distribution the histogram
    registry already renders."""
    from ..exec.datapath import HOPS, process_totals
    totals = process_totals()
    fam_b = MetricFamily(
        "presto_tpu_datapath_bytes_total", "counter",
        "bytes attributed per data-path hop "
        "(exec/datapath.py; see DESIGN.md 'Data-path attribution')")
    fam_s = MetricFamily(
        "presto_tpu_datapath_seconds_total", "counter",
        "wall attributed per data-path hop (bytes/seconds ratio over "
        "a scrape window = the hop's achieved throughput)")
    fam_i = MetricFamily(
        "presto_tpu_datapath_observations_total", "counter",
        "hop observations recorded (splits staged, pages coded, "
        "fetches, drains)")
    for hop in HOPS:
        h = totals[hop]
        fam_b.add(h.bytes, {"hop": hop})
        fam_s.add(round(h.wall_us / 1e6, 6), {"hop": hop})
        fam_i.add(h.invocations, {"hop": hop})
    return [fam_b, fam_s, fam_i]


def accuracy_families() -> List[MetricFamily]:
    """Estimate-accuracy lifetime totals (exec/accuracy.py), exported
    by BOTH tiers with a stable zero shape: complete records folded,
    misestimates beyond the band by direction, and the worst q-error
    seen -- beside the Q_ERROR_BUCKETS distribution the histogram
    registry already renders."""
    from ..exec.accuracy import UNITS, process_totals
    totals = process_totals()
    fam_r = MetricFamily(
        "presto_tpu_accuracy_records_total", "counter",
        "complete estimate-vs-actual records folded per unit "
        "(exec/accuracy.py; see DESIGN.md 'Estimate accuracy')")
    fam_m = MetricFamily(
        "presto_tpu_misestimates_total", "counter",
        "records whose q-error exceeded the band, by unit and "
        "direction (under = planner guessed low)")
    fam_w = MetricFamily(
        "presto_tpu_worst_q_error", "gauge",
        "lifetime worst q-error observed per unit (monotonic; 0 "
        "until the first complete record)")
    for unit in UNITS:
        t = totals[unit]
        fam_r.add(t["records"], {"unit": unit})
        for d in ("under", "over"):
            fam_m.add(t[d], {"unit": unit, "direction": d})
        fam_w.add(round(t["worstQError"], 4), {"unit": unit})
    return [fam_r, fam_m, fam_w]


def narrowing_families() -> List[MetricFamily]:
    """Narrow-width execution lifetime totals (plan/widths.py), exported
    by both tiers next to the plan-cache hit/miss counters so staging
    savings and compile savings read off one scrape."""
    from ..plan.widths import narrowing_totals
    t = narrowing_totals()
    return [
        MetricFamily("presto_tpu_narrowed_bytes_saved_total", "counter",
                     "host->HBM staging bytes saved by narrow-width "
                     "execution").add(t["bytes_saved"]),
        MetricFamily("presto_tpu_narrowed_columns_total", "counter",
                     "scan columns staged at a narrowed physical "
                     "lane").add(t["columns"]),
    ]


# -- suppressed handler errors ------------------------------------------
#
# Server-tier contract (enforced statically by tpulint's S001 pass): a
# request handler/background loop that intentionally survives an
# exception must still LEAVE A TRACE -- one debug log line plus a
# lifetime counter labelled by (component, site), exported on
# /v1/metrics by both tiers. "Swallowed but counted" is observable;
# "swallowed" is a silent outage.

_SUPPRESSED_LOCK = OrderedLock("metrics._SUPPRESSED_LOCK")
_SUPPRESSED: Dict[Tuple[str, str], int] = {}
_log = logging.getLogger("presto_tpu.server")


def record_suppressed(component: str, site: str,
                      exc: Optional[BaseException] = None) -> None:
    """Count (and debug-log) an intentionally survived exception.
    Never raises: this runs inside except blocks on cleanup paths."""
    with _SUPPRESSED_LOCK:
        key = (component, site)
        _SUPPRESSED[key] = _SUPPRESSED.get(key, 0) + 1
    if exc is not None:
        try:
            _log.debug("suppressed error in %s.%s: %s: %s",
                       component, site, type(exc).__name__, exc)
        except Exception:  # tpulint: disable=S001 - logging teardown
            pass


def suppressed_error_totals() -> Dict[Tuple[str, str], int]:
    with _SUPPRESSED_LOCK:
        return dict(_SUPPRESSED)


def suppressed_error_families() -> List[MetricFamily]:
    """One counter family, (component, site)-labelled, shared by the
    coordinator and worker scrape endpoints."""
    fam = MetricFamily(
        "presto_tpu_suppressed_errors_total", "counter",
        "handler/background-loop exceptions intentionally survived "
        "(logged + counted; see tpulint S001)")
    totals = suppressed_error_totals()
    for (component, site), n in sorted(totals.items()):
        fam.add(n, {"component": component, "site": site})
    if not totals:  # families always carry >= 1 sample (scrape shape
        # is stable from the first request on)
        fam.add(0, {"component": "none", "site": "none"})
    return [fam]


def tracing_families() -> List[MetricFamily]:
    """Tracer health, exported by BOTH tiers: spans recorded, traces
    evicted at capacity, spans dropped by a broken tracer -- the
    counters that tell an operator whether the trace they are about to
    pull is complete."""
    from .tracing import tracing_totals
    t = tracing_totals()
    return [
        MetricFamily("presto_tpu_trace_spans_total", "counter",
                     "spans recorded by the process tracer").add(
                         t["spans"]),
        MetricFamily("presto_tpu_traces_evicted_total", "counter",
                     "traces evicted at tracer capacity "
                     "(least-recently-updated out)").add(t["evicted"]),
        MetricFamily("presto_tpu_trace_spans_dropped_total", "counter",
                     "spans lost to a tracer that raised "
                     "(see suppressed_errors{component=tracing})").add(
                         t["dropped"]),
    ]


def flight_recorder_families() -> List[MetricFamily]:
    """Flight-recorder health: events recorded, auto-dumps written
    (labelled by trigger reason: failed | slow | perf_regression), and
    dump files evicted by the on-disk retention cap."""
    from .flight_recorder import flight_recorder_totals
    t = flight_recorder_totals()
    fam_d = MetricFamily(
        "presto_tpu_flight_recorder_dumps_total", "counter",
        "automatic slow/failed/perf-regression JSONL dumps, by trigger "
        "reason")
    dumps = t["dumps"]
    for reason in sorted(set(dumps) | {"failed", "slow",
                                       "perf_regression", "stuck"}):
        fam_d.add(dumps.get(reason, 0), {"reason": reason})
    return [
        MetricFamily("presto_tpu_flight_recorder_events_total", "counter",
                     "structured events appended to the flight-recorder "
                     "ring").add(t["events"]),
        fam_d,
        MetricFamily("presto_tpu_flight_dumps_evicted_total", "counter",
                     "dump files deleted oldest-first by the "
                     "PRESTO_TPU_FLIGHT_MAX_DUMPS retention cap").add(
                         t.get("evicted", 0)),
    ]


def query_history_families() -> List[MetricFamily]:
    """Query-history archive + perf-sentinel families, exported by BOTH
    tiers: archive size, lifetime records archived, and regression
    breaches per gated metric. Every sentinel metric gets a sample
    (zeros included) so the scrape shape is stable from the first
    request on and scripts/scrape_metrics.py's ``history`` section can
    always report deltas."""
    from ..exec.perfgate import SENTINEL_SPECS
    from .history import (get_history_archive, history_totals,
                          perf_regression_totals)
    regressions = perf_regression_totals()
    fam_r = MetricFamily(
        "presto_tpu_perf_regressions_total", "counter",
        "per-fingerprint baseline breaches caught by the in-engine "
        "perf sentinel, by metric (server/history.py + exec/perfgate.py)")
    metrics = {s.name for s in SENTINEL_SPECS} | set(regressions)
    for m in sorted(metrics):
        fam_r.add(regressions.get(m, 0), {"metric": m})
    return [
        MetricFamily("presto_tpu_query_history_entries", "gauge",
                     "completed-query records currently retained by "
                     "this process's history archive").add(
                         get_history_archive().size()),
        MetricFamily("presto_tpu_query_history_records_total", "counter",
                     "completed-query records archived since process "
                     "start").add(history_totals()["records"]),
        fam_r,
    ]


def kernel_audit_families() -> List[MetricFamily]:
    """Staging-time kernel-audit totals (audit/staged.py), exported by
    BOTH tiers: findings per IR pass plus kernels audited. Every
    registered pass code gets a sample (zeros included) so the scrape
    shape is stable from the first request on."""
    from ..audit.core import all_passes
    from ..audit.staged import kernel_audit_totals
    t = kernel_audit_totals()
    findings = t["findings"]
    fam = MetricFamily(
        "presto_tpu_kernel_audit_findings_total", "counter",
        "IR-audit findings surfaced to queries, by pass "
        "(kernaudit; see DESIGN.md 'Kernel IR auditing')")
    codes = {p.code for p in all_passes()} | set(findings)
    for code in sorted(codes):
        fam.add(findings.get(code, 0), {"pass": code})
    return [
        fam,
        MetricFamily("presto_tpu_kernel_audit_kernels_total", "counter",
                     "staged kernels traced and audited (memo hits "
                     "excluded)").add(t["kernels"]),
    ]


def donation_families() -> List[MetricFamily]:
    """Proven-safe buffer-donation totals (exec/donation.py), exported
    by BOTH tiers with a stable zero shape: donated dispatches, HBM
    bytes aliased in place of fresh output allocations, and donation
    -path errors that collapsed to the undonated dispatch."""
    from ..exec.donation import donation_totals
    t = donation_totals()
    return [
        MetricFamily("presto_tpu_donations_total", "counter",
                     "region dispatches that ran the donating form "
                     "(K006-proven donate_argnums wrapper)").add(
                         t["donations"]),
        MetricFamily("presto_tpu_donated_bytes_total", "counter",
                     "HBM bytes aliased input-to-output by proven-safe "
                     "buffer donation instead of freshly allocated "
                     "(see DESIGN.md 'Buffer donation')").add(
                         t["donated_bytes"]),
        MetricFamily("presto_tpu_donation_fallbacks_total", "counter",
                     "donation-path errors that fell back to the "
                     "normal undonated dispatch (fallback, never "
                     "failure)").add(t["fallbacks"]),
    ]


def timeline_families() -> List[MetricFamily]:
    """Execution-timeline totals (exec/timeline.py), exported by BOTH
    tiers with a stable zero shape: lifetime interval/drop/query
    counters plus the last completed query's occupancy headline
    (overlap fraction and device-idle wall) -- the gauges the async
    -pipeline ROADMAP item is sentineled against."""
    from ..exec.timeline import last_occupancy, timeline_totals
    t = timeline_totals()
    last = last_occupancy()
    return [
        MetricFamily("presto_tpu_timeline_intervals_total", "counter",
                     "execution-timeline intervals retained across "
                     "queries (exec/timeline.py; see DESIGN.md "
                     "'Execution timeline & occupancy')").add(
                         t["intervals"]),
        MetricFamily("presto_tpu_timeline_dropped_total", "counter",
                     "intervals dropped by the per-query cap or "
                     "totals-only degradation (never a query "
                     "failure)").add(t["dropped"]),
        MetricFamily("presto_tpu_timeline_queries_total", "counter",
                     "queries that contributed a timeline slice").add(
                         t["queries"]),
        MetricFamily("presto_tpu_overlap_fraction", "gauge",
                     "last query's host-staging/device-dispatch "
                     "overlap fraction (0 = strictly serial pipeline; "
                     "the async-ingest baseline)").add(
                         float(last.get("overlapFraction", 0.0))),
        MetricFamily("presto_tpu_device_idle_us", "gauge",
                     "last query's device-idle wall within the "
                     "timeline extent (the bubble the occupancy "
                     "verdict attributes per hop)").add(
                         int(last.get("deviceIdleUs", 0))),
    ]


def live_introspection_families(workers_alive: Optional[int] = None
                                ) -> List[MetricFamily]:
    """Live-cluster introspection gauges + the stuck-progress counter,
    exported by BOTH tiers: in-flight tasks known to this process's
    progress registry (exec/progress.py), the caller's view of alive
    workers (the worker passes 1 -- itself; the statement tier passes
    its cached /v1/status probe count), and lifetime stuck-progress
    watchdog firings (server/watchdog.py)."""
    from ..exec.progress import live_task_count
    from .watchdog import stuck_totals
    fams = [
        MetricFamily("presto_tpu_running_tasks", "gauge",
                     "in-flight query/task progress entries this "
                     "process is tracking").add(live_task_count()),
        MetricFamily("presto_tpu_stuck_queries_total", "counter",
                     "queries/tasks whose progress last-advance age "
                     "exceeded stuck_query_threshold_ms "
                     "(stuck-progress watchdog firings)").add(
                         stuck_totals()),
    ]
    if workers_alive is not None:
        fams.insert(1, MetricFamily(
            "presto_tpu_cluster_workers_alive", "gauge",
            "workers this node currently believes alive (the worker "
            "reports itself; the statement tier its last /v1/status "
            "probe)").add(int(workers_alive)))
    return fams


def fleet_families(workers_draining: Optional[int] = None
                   ) -> List[MetricFamily]:
    """Elastic-fleet accounting, exported by BOTH tiers with a stable
    zero shape: membership churn (workers joined/left through the
    discovery service), announcer re-registration retries, speculative
    re-execution outcomes (launched/wins/losses), coordinator
    failovers, and -- when the caller knows it -- the draining-worker
    gauge (the worker reports its own drain state; the statement tier
    its last /v1/cluster probe's DRAINING count)."""
    from .coordinator import speculation_totals
    from .discovery import announce_retry_totals, fleet_membership_totals
    from .resource_manager import failover_totals
    member = fleet_membership_totals()
    spec = speculation_totals()
    fams = [
        MetricFamily("presto_tpu_fleet_workers_joined_total", "counter",
                     "distinct worker announcements accepted by this "
                     "process's discovery service").add(member["joined"]),
        MetricFamily("presto_tpu_fleet_workers_left_total", "counter",
                     "worker unannouncements (graceful goodbyes) "
                     "accepted by this process's discovery "
                     "service").add(member["left"]),
        MetricFamily("presto_tpu_announce_retries_total", "counter",
                     "failed worker announcements retried on the "
                     "backoff schedule (utils/backoff.py)").add(
                         announce_retry_totals()),
        MetricFamily("presto_tpu_speculation_launched_total", "counter",
                     "speculative task attempts submitted for "
                     "stragglers").add(spec["launched"]),
        MetricFamily("presto_tpu_speculation_wins_total", "counter",
                     "speculative attempts that finished before their "
                     "straggling original").add(spec["wins"]),
        MetricFamily("presto_tpu_speculation_losses_total", "counter",
                     "speculative attempts beaten by their "
                     "original").add(spec["losses"]),
        MetricFamily("presto_tpu_coordinator_failovers_total", "counter",
                     "standby-coordinator takeovers after a primary "
                     "heartbeat lapse "
                     "(resource_manager.StandbyCoordinator)").add(
                         failover_totals()),
    ]
    if workers_draining is not None:
        fams.append(MetricFamily(
            "presto_tpu_fleet_workers_draining", "gauge",
            "workers currently in the DRAINING state (the worker "
            "reports itself; the statement tier its last probe)").add(
                int(workers_draining)))
    return fams


def failpoint_families() -> List[MetricFamily]:
    """Fault-injection accounting, exported by BOTH tiers: lifetime
    fired-fault counts per (site, action) plus the currently-armed
    gauge. The chaos harness's third invariant -- every injected fault
    accounted for -- audits against exactly these samples."""
    from ..failpoints import armed_count, failpoint_totals
    fam = MetricFamily(
        "presto_tpu_failpoint_hits_total", "counter",
        "fault injections fired, by (site, action) "
        "(failpoints subsystem; see DESIGN.md 'Fault injection')")
    totals = failpoint_totals()
    for (site, action), n in sorted(totals.items()):
        fam.add(n, {"site": site, "action": action})
    if not totals:  # stable scrape shape from the first request on
        fam.add(0, {"site": "none", "action": "none"})
    return [
        fam,
        MetricFamily("presto_tpu_failpoints_armed", "gauge",
                     "failpoint sites currently armed").add(
                         armed_count()),
    ]


def lock_families() -> List[MetricFamily]:
    """Lock-order witness accounting, exported by BOTH tiers: the
    process-lifetime inversion counter (a stable zero on a healthy
    tier -- the chaos soak and the armed tier-1 cluster test fail on
    anything else) plus the armed gauge, so a scrape shows whether
    zero means "clean under watch" or "witness off"."""
    from ..utils import locks as _locks
    return [
        MetricFamily(
            "presto_tpu_lock_order_violations_total", "counter",
            "lock-order inversions detected at acquire time by the "
            "runtime witness (utils/locks.py; see DESIGN.md "
            "'Concurrency auditing')").add(
                _locks.witness_violations_total()),
        MetricFamily(
            "presto_tpu_lock_witness_armed", "gauge",
            "1 while the lock-order witness is armed").add(
                1 if _locks.ARMED else 0),
    ]


def uptime_family(started_at: float, role: str) -> MetricFamily:
    import time
    return MetricFamily("presto_tpu_uptime_seconds", "gauge",
                        f"{role} uptime").add(
                            round(time.time() - started_at, 1))


def render_prometheus(families: List[MetricFamily],
                      openmetrics: bool = False) -> bytes:
    """Default: classic text format 0.0.4, exemplar-free (valid for a
    stock Prometheus scraper). `openmetrics=True` (the handlers pass it
    when the Accept header asks for application/openmetrics-text)
    renders bucket exemplars and the terminating ``# EOF``."""
    lines: List[str] = []
    for f in families:
        lines.extend(f.render(exemplars=openmetrics))
    if openmetrics:
        lines.append("# EOF")
    return ("\n".join(lines) + "\n").encode()


def negotiate_exposition(accept_header: Optional[str]
                         ) -> Tuple[bool, str]:
    """(openmetrics?, content type) from a scrape's Accept header --
    the one negotiation both tiers' /v1/metrics handlers share."""
    if accept_header and "openmetrics" in accept_header:
        return True, CONTENT_TYPE_OPENMETRICS
    return False, CONTENT_TYPE


_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _histogram_base(name: str, typed: Dict[str, str]) -> Optional[str]:
    """The histogram family a ``_bucket``/``_sum``/``_count`` sample
    belongs to, when one is declared."""
    for suf in _HIST_SUFFIXES:
        if name.endswith(suf):
            base = name[: -len(suf)]
            if typed.get(base) == "histogram":
                return base
    return None


def parse_prometheus(text: str) -> Dict[str, Dict[str, float]]:
    """Exposition text -> {family: {sample_key: value}} where
    sample_key is '' for unlabelled samples or the rendered label set.
    Histogram sub-samples keep their full ``<base>_bucket``/``_sum``/
    ``_count`` names as the family key (their ``# TYPE`` line is the
    base name); OpenMetrics exemplar suffixes (`` # {...} v``) are
    stripped before value parsing. Used by scripts/scrape_metrics.py
    and the test suite; raises ValueError on lines that are neither
    comments nor samples (the 'valid Prometheus text' check)."""
    out: Dict[str, Dict[str, float]] = {}
    typed: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                mtype = parts[3] if len(parts) > 3 else "untyped"
                if mtype not in ("counter", "gauge", "histogram",
                                 "summary", "untyped"):
                    raise ValueError(f"bad TYPE line: {raw!r}")
                typed[parts[2]] = mtype
            continue
        # exemplar suffix: everything from the last " # {" on is the
        # OpenMetrics exemplar annotation, not part of the sample
        ex_at = line.rfind(" # {")
        if ex_at != -1:
            line = line[:ex_at].rstrip()
        name, _, rest = line.partition("{")
        if rest:  # labelled sample
            labels, _, valpart = rest.rpartition("}")
            value = valpart.strip()
            key = "{" + labels + "}"
        else:
            fields = line.split()
            if len(fields) not in (2, 3):  # optional timestamp
                raise ValueError(f"bad sample line: {raw!r}")
            name, value = fields[0], fields[1]
            key = ""
        fam = name
        try:
            fval = float(value)
        except ValueError as e:
            raise ValueError(f"bad value in line: {raw!r}") from e
        if fam not in typed and _histogram_base(fam, typed) is None:
            raise ValueError(f"sample {fam!r} before its # TYPE line")
        out.setdefault(fam, {})[key] = fval
    return out
