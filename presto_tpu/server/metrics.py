"""Prometheus text-format metrics: the one emitter both tiers share.

Reference surface: the native worker's PrometheusStatsReporter
(presto_cpp/main/PrometheusStatsReporter.cpp) and PrestoServer's
registerHttpEndpoints wiring a scrapeable endpoint; on the Java side
the JMX connector exports the same counters. Both the coordinator
(statement server) and the worker serve ``GET /v1/metrics`` rendering
through this module, so scrape format and naming conventions cannot
drift between tiers.

Format is the Prometheus exposition text format v0.0.4: per family a
``# HELP`` line, a ``# TYPE`` line (counter | gauge), then one sample
per label set. Labels are rendered sorted for deterministic scrapes
(scripts/scrape_metrics.py diffs two scrapes textually-parsed).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple, Union

__all__ = ["MetricFamily", "render_prometheus", "parse_prometheus",
           "plan_cache_families", "narrowing_families", "uptime_family",
           "record_suppressed", "suppressed_error_families",
           "suppressed_error_totals", "tracing_families",
           "flight_recorder_families", "kernel_audit_families",
           "CONTENT_TYPE"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_LabelSample = Tuple[Dict[str, str], Union[int, float]]


class MetricFamily:
    """One metric family: name, type, help, and samples (optionally
    labelled)."""

    def __init__(self, name: str, mtype: str, help_: str):
        assert mtype in ("counter", "gauge"), mtype
        self.name = name
        self.mtype = mtype
        self.help = help_
        self.samples: List[_LabelSample] = []

    def add(self, value: Union[int, float],
            labels: Optional[Dict[str, str]] = None) -> "MetricFamily":
        self.samples.append((dict(labels or {}), value))
        return self

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.mtype}"]
        for labels, value in self.samples:
            if labels:
                lab = ",".join(
                    f'{k}="{_escape(v)}"'
                    for k, v in sorted(labels.items()))
                lines.append(f"{self.name}{{{lab}}} {_num(value)}")
            else:
                lines.append(f"{self.name} {_num(value)}")
        return lines


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def _num(v: Union[int, float]) -> str:
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    return repr(round(float(v), 6))


def plan_cache_families() -> List[MetricFamily]:
    """The compiled-plan cache families both tiers export -- ONE
    builder so the names cannot drift between coordinator and worker."""
    from ..exec.plan_cache import cache_stats
    st = cache_stats()
    return [
        MetricFamily("presto_tpu_plan_cache_entries", "gauge",
                     "compiled-plan cache entries").add(st["entries"]),
        MetricFamily("presto_tpu_plan_cache_hits_total", "counter",
                     "compiled-plan cache hits").add(st["hits"]),
        MetricFamily("presto_tpu_plan_cache_misses_total", "counter",
                     "compiled-plan cache misses").add(st["misses"]),
    ]


def narrowing_families() -> List[MetricFamily]:
    """Narrow-width execution lifetime totals (plan/widths.py), exported
    by both tiers next to the plan-cache hit/miss counters so staging
    savings and compile savings read off one scrape."""
    from ..plan.widths import narrowing_totals
    t = narrowing_totals()
    return [
        MetricFamily("presto_tpu_narrowed_bytes_saved_total", "counter",
                     "host->HBM staging bytes saved by narrow-width "
                     "execution").add(t["bytes_saved"]),
        MetricFamily("presto_tpu_narrowed_columns_total", "counter",
                     "scan columns staged at a narrowed physical "
                     "lane").add(t["columns"]),
    ]


# -- suppressed handler errors ------------------------------------------
#
# Server-tier contract (enforced statically by tpulint's S001 pass): a
# request handler/background loop that intentionally survives an
# exception must still LEAVE A TRACE -- one debug log line plus a
# lifetime counter labelled by (component, site), exported on
# /v1/metrics by both tiers. "Swallowed but counted" is observable;
# "swallowed" is a silent outage.

_SUPPRESSED_LOCK = threading.Lock()
_SUPPRESSED: Dict[Tuple[str, str], int] = {}
_log = logging.getLogger("presto_tpu.server")


def record_suppressed(component: str, site: str,
                      exc: Optional[BaseException] = None) -> None:
    """Count (and debug-log) an intentionally survived exception.
    Never raises: this runs inside except blocks on cleanup paths."""
    with _SUPPRESSED_LOCK:
        key = (component, site)
        _SUPPRESSED[key] = _SUPPRESSED.get(key, 0) + 1
    if exc is not None:
        try:
            _log.debug("suppressed error in %s.%s: %s: %s",
                       component, site, type(exc).__name__, exc)
        except Exception:  # tpulint: disable=S001 - logging teardown
            pass


def suppressed_error_totals() -> Dict[Tuple[str, str], int]:
    with _SUPPRESSED_LOCK:
        return dict(_SUPPRESSED)


def suppressed_error_families() -> List[MetricFamily]:
    """One counter family, (component, site)-labelled, shared by the
    coordinator and worker scrape endpoints."""
    fam = MetricFamily(
        "presto_tpu_suppressed_errors_total", "counter",
        "handler/background-loop exceptions intentionally survived "
        "(logged + counted; see tpulint S001)")
    totals = suppressed_error_totals()
    for (component, site), n in sorted(totals.items()):
        fam.add(n, {"component": component, "site": site})
    if not totals:  # families always carry >= 1 sample (scrape shape
        # is stable from the first request on)
        fam.add(0, {"component": "none", "site": "none"})
    return [fam]


def tracing_families() -> List[MetricFamily]:
    """Tracer health, exported by BOTH tiers: spans recorded, traces
    evicted at capacity, spans dropped by a broken tracer -- the
    counters that tell an operator whether the trace they are about to
    pull is complete."""
    from .tracing import tracing_totals
    t = tracing_totals()
    return [
        MetricFamily("presto_tpu_trace_spans_total", "counter",
                     "spans recorded by the process tracer").add(
                         t["spans"]),
        MetricFamily("presto_tpu_traces_evicted_total", "counter",
                     "traces evicted at tracer capacity "
                     "(least-recently-updated out)").add(t["evicted"]),
        MetricFamily("presto_tpu_trace_spans_dropped_total", "counter",
                     "spans lost to a tracer that raised "
                     "(see suppressed_errors{component=tracing})").add(
                         t["dropped"]),
    ]


def flight_recorder_families() -> List[MetricFamily]:
    """Flight-recorder health: events recorded and auto-dumps written,
    labelled by trigger reason (failed | slow)."""
    from .flight_recorder import flight_recorder_totals
    t = flight_recorder_totals()
    fam_d = MetricFamily(
        "presto_tpu_flight_recorder_dumps_total", "counter",
        "automatic slow/failed-query JSONL dumps, by trigger reason")
    dumps = t["dumps"]
    for reason in sorted(set(dumps) | {"failed", "slow"}):
        fam_d.add(dumps.get(reason, 0), {"reason": reason})
    return [
        MetricFamily("presto_tpu_flight_recorder_events_total", "counter",
                     "structured events appended to the flight-recorder "
                     "ring").add(t["events"]),
        fam_d,
    ]


def kernel_audit_families() -> List[MetricFamily]:
    """Staging-time kernel-audit totals (audit/staged.py), exported by
    BOTH tiers: findings per IR pass plus kernels audited. Every
    registered pass code gets a sample (zeros included) so the scrape
    shape is stable from the first request on."""
    from ..audit.core import all_passes
    from ..audit.staged import kernel_audit_totals
    t = kernel_audit_totals()
    findings = t["findings"]
    fam = MetricFamily(
        "presto_tpu_kernel_audit_findings_total", "counter",
        "IR-audit findings surfaced to queries, by pass "
        "(kernaudit; see DESIGN.md 'Kernel IR auditing')")
    codes = {p.code for p in all_passes()} | set(findings)
    for code in sorted(codes):
        fam.add(findings.get(code, 0), {"pass": code})
    return [
        fam,
        MetricFamily("presto_tpu_kernel_audit_kernels_total", "counter",
                     "staged kernels traced and audited (memo hits "
                     "excluded)").add(t["kernels"]),
    ]


def uptime_family(started_at: float, role: str) -> MetricFamily:
    import time
    return MetricFamily("presto_tpu_uptime_seconds", "gauge",
                        f"{role} uptime").add(
                            round(time.time() - started_at, 1))


def render_prometheus(families: List[MetricFamily]) -> bytes:
    lines: List[str] = []
    for f in families:
        lines.extend(f.render())
    return ("\n".join(lines) + "\n").encode()


def parse_prometheus(text: str) -> Dict[str, Dict[str, float]]:
    """Exposition text -> {family: {sample_key: value}} where
    sample_key is '' for unlabelled samples or the rendered label set.
    Used by scripts/scrape_metrics.py and the test suite; raises
    ValueError on lines that are neither comments nor samples (the
    'valid Prometheus text' check)."""
    out: Dict[str, Dict[str, float]] = {}
    typed: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                mtype = parts[3] if len(parts) > 3 else "untyped"
                if mtype not in ("counter", "gauge", "histogram",
                                 "summary", "untyped"):
                    raise ValueError(f"bad TYPE line: {raw!r}")
                typed[parts[2]] = mtype
            continue
        name, _, rest = line.partition("{")
        if rest:  # labelled sample
            labels, _, valpart = rest.rpartition("}")
            value = valpart.strip()
            key = "{" + labels + "}"
        else:
            fields = line.split()
            if len(fields) not in (2, 3):  # optional timestamp
                raise ValueError(f"bad sample line: {raw!r}")
            name, value = fields[0], fields[1]
            key = ""
        fam = name
        try:
            fval = float(value)
        except ValueError as e:
            raise ValueError(f"bad value in line: {raw!r}") from e
        if fam not in typed:
            raise ValueError(f"sample {fam!r} before its # TYPE line")
        out.setdefault(fam, {})[key] = fval
    return out
