"""Dispatcher: query admission, resource-group queueing, execution.

Reference surface: dispatcher/DispatchManager.java:68 (createQuery:234
parses, picks a resource group, queues), resourceGroups'
InternalResourceGroupManager (hierarchical admission: hard concurrency
+ queue caps per group), and QueuedStatementResource's queue-then-
redirect flow.

Slice here: named resource groups with hard_concurrency_limit /
max_queued / memory gate, selected by user or source (the file-based
selector pattern); a query BLOCKS in its group's queue until a slot
frees (the reference long-polls the same wait), then runs through the
coordinator or local runner. Events fire at create/complete
(QueryCreated/QueryCompleted)."""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from .events import event_listeners

__all__ = ["ResourceGroup", "Dispatcher", "QueryRejected"]


class QueryRejected(RuntimeError):
    """Admission failure: queue full or no matching group."""


@dataclasses.dataclass
class ResourceGroup:
    """InternalResourceGroup analog (flat; hierarchy composes by
    name prefixes in the selector)."""
    name: str
    hard_concurrency_limit: int = 4
    max_queued: int = 16

    def __post_init__(self):
        self._running = 0
        self._queued = 0
        self._cv = threading.Condition()

    def stats(self) -> Dict[str, int]:
        with self._cv:
            return {"running": self._running, "queued": self._queued,
                    "hardConcurrencyLimit": self.hard_concurrency_limit,
                    "maxQueued": self.max_queued}

    def acquire(self, timeout: Optional[float] = None):
        with self._cv:
            if self._queued >= self.max_queued:
                raise QueryRejected(
                    f"resource group {self.name!r} queue is full "
                    f"({self.max_queued})")
            self._queued += 1
            deadline = None if timeout is None else time.time() + timeout
            try:
                while self._running >= self.hard_concurrency_limit:
                    remaining = None if deadline is None \
                        else deadline - time.time()
                    if remaining is not None and remaining <= 0:
                        raise QueryRejected(
                            f"query queued in {self.name!r} longer than "
                            f"{timeout}s")
                    self._cv.wait(remaining)
            finally:
                self._queued -= 1
            self._running += 1

    def release(self):
        with self._cv:
            self._running -= 1
            # notify_all, not notify: a waiter that times out may have
            # just consumed the single notify without taking the slot,
            # which would leave another queued waiter blocked forever.
            self._cv.notify_all()


class Dispatcher:
    """DispatchManager analog: select a group, admit, execute, account.

    `executor(query_id, query)` does the actual work (the coordinator's
    execute or a local run_query closure); the dispatcher owns only
    admission and lifecycle events."""

    def __init__(self, groups: Optional[List[ResourceGroup]] = None,
                 selector: Optional[Callable[[Dict], str]] = None):
        self.groups = {g.name: g for g in (groups or
                                           [ResourceGroup("global")])}
        self._selector = selector or (lambda session: "global")

    def group_stats(self) -> Dict[str, Dict[str, int]]:
        return {name: g.stats() for name, g in self.groups.items()}

    def submit(self, executor: Callable[[str], object],
               session: Optional[Dict] = None,
               query_text: str = "",
               queue_timeout: Optional[float] = None,
               query_id: Optional[str] = None):
        """Admit + run one query synchronously (the reference's async
        dispatch is its HTTP shell; the admission semantics live here).
        Raises QueryRejected when the group's queue is full. The caller
        may supply the query id (the statement resource mints ids at
        POST time, before admission, like QueuedStatementResource)."""
        session = session or {}
        group_name = self._selector(session)
        group = self.groups.get(group_name)
        if group is None:
            raise QueryRejected(f"no resource group {group_name!r}")
        query_id = query_id or f"q-{uuid.uuid4().hex[:12]}"
        events = event_listeners()
        events.query_created(query_id, query_text,
                             session.get("user", ""))
        group.acquire(queue_timeout)
        t0 = time.time()
        try:
            result = executor(query_id)
        except Exception as e:
            events.query_completed(query_id, "FAILED",
                                   wall_s=time.time() - t0, error=str(e))
            raise
        finally:
            group.release()
        rows = getattr(result, "row_count", 0)
        events.query_completed(query_id, "FINISHED", rows=rows,
                               wall_s=time.time() - t0)
        return result
