"""Dispatcher: query admission, resource-group queueing, execution.

Reference surface: dispatcher/DispatchManager.java:68 (createQuery:234
parses, picks a resource group, queues), resourceGroups'
InternalResourceGroupManager (hierarchical admission: hard concurrency
+ queue caps per group), and QueuedStatementResource's queue-then-
redirect flow.

Slice here: named resource groups with hard_concurrency_limit /
max_queued / memory gate, selected by user or source (the file-based
selector pattern); a query BLOCKS in its group's queue until a slot
frees (the reference long-polls the same wait), then runs through the
coordinator or local runner. Events fire at create/complete
(QueryCreated/QueryCompleted)."""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from .. import failpoints
from ..utils.locks import OrderedLock
from .events import event_listeners

__all__ = ["ResourceGroup", "Dispatcher", "QueryRejected",
           "LATENCY_CLASSES", "latency_class_groups",
           "latency_class_selector"]


class QueryRejected(RuntimeError):
    """Admission failure: queue full or no matching group."""


@dataclasses.dataclass
class ResourceGroup:
    """InternalResourceGroup analog, now HIERARCHICAL: a query admitted
    into a leaf holds one concurrency slot (and its memory budget) in
    the leaf AND every ancestor, so parent limits cap whole subtrees
    (InternalResourceGroup.java's canRunMore chain). Admission among
    competing queued leaves under a constrained ancestor is
    weighted-fair: the eligible leaf with the LOWEST running/weight
    ratio goes first (ties FIFO), the reference's WEIGHTED_FAIR
    scheduling policy."""
    name: str
    hard_concurrency_limit: int = 4
    max_queued: int = 16
    soft_memory_limit_bytes: Optional[int] = None
    scheduling_weight: int = 1
    # admission preemption (latency classes): among capacity-eligible
    # waiters a HIGHER-priority leaf always admits first -- interactive
    # traffic preempts queued scans at the slot boundary (the
    # cooperative analog of the reference's query preemption)
    priority: int = 0

    # tpulint C001: admission state is written through WHATEVER
    # receiver walks the tree (g/root/leaf) while holding the ONE
    # per-tree condition -- _cv is a shared lock, any receiver counts
    _GUARDED_BY = {"_cv": ("_running", "_queued", "_mem_used",
                           "_ticket", "_waiters")}
    _GUARDED_BY_SHARED = ("_cv",)

    def __post_init__(self):
        self._running = 0
        self._queued = 0
        self._mem_used = 0
        self.parent: Optional["ResourceGroup"] = None
        self.children: Dict[str, "ResourceGroup"] = {}
        # one condition per TREE (the root's); shared by add_child
        # the tree's condition wraps an OrderedLock so admission waits
        # ride the runtime lock-order witness like every other lock
        # (Condition probes ownership via OrderedLock._is_owned)
        self._cv = threading.Condition(
            OrderedLock("dispatcher.ResourceGroup._cv"))
        self._waiters: List[tuple] = []  # (ticket, leaf) FIFO registry
        self._ticket = 0

    # -- tree construction -------------------------------------------------

    def add_child(self, child: "ResourceGroup") -> "ResourceGroup":
        child.parent = self
        root = self._root()
        child._cv = root._cv
        for g in child._subtree():
            g._cv = root._cv
        self.children[child.name] = child
        return child

    def _root(self) -> "ResourceGroup":
        g = self
        while g.parent is not None:
            g = g.parent
        return g

    def _subtree(self):
        yield self
        for c in self.children.values():
            yield from c._subtree()

    def _chain(self):
        g = self
        while g is not None:
            yield g
            g = g.parent

    def find(self, dotted: str) -> Optional["ResourceGroup"]:
        """Resolve "etl.nightly" relative to this group."""
        g = self
        for part in dotted.split("."):
            if part == g.name and g is self:
                continue
            nxt = g.children.get(part)
            if nxt is None:
                return None
            g = nxt
        return g

    def stats(self) -> Dict[str, int]:
        with self._cv:
            out = {"running": self._running, "queued": self._queued,
                   "hardConcurrencyLimit": self.hard_concurrency_limit,
                   "maxQueued": self.max_queued,
                   "schedulingWeight": self.scheduling_weight,
                   "priority": self.priority,
                   "memoryUsedBytes": self._mem_used}
            if self.soft_memory_limit_bytes is not None:
                out["softMemoryLimitBytes"] = self.soft_memory_limit_bytes
            return out

    # -- admission ---------------------------------------------------------

    def _capacity_now(self, mem: int) -> bool:
        for g in self._chain():
            if g._running >= g.hard_concurrency_limit:
                return False
            if g.soft_memory_limit_bytes is not None and \
                    g._mem_used + mem > g.soft_memory_limit_bytes:
                return False
        return True

    def acquire(self, timeout: Optional[float] = None, mem: int = 0):
        root = self._root()
        with self._cv:
            for g in self._chain():
                if g.soft_memory_limit_bytes is not None and \
                        mem > g.soft_memory_limit_bytes:
                    raise QueryRejected(
                        f"query memory {mem} exceeds group "
                        f"{g.name!r} limit {g.soft_memory_limit_bytes}")
                if g._queued >= g.max_queued:
                    raise QueryRejected(
                        f"resource group {g.name!r} queue is full "
                        f"({g.max_queued})")
            for g in self._chain():
                g._queued += 1
            root._ticket += 1
            me = (root._ticket, self, mem)
            root._waiters.append(me)
            deadline = None if timeout is None else time.time() + timeout

            def my_turn() -> bool:
                if not self._capacity_now(mem):
                    return False
                # priority-then-weighted-fair: among capacity-eligible
                # waiters the highest-priority leaf admits first
                # (latency-class preemption), ties by lowest
                # running/weight, then FIFO ticket
                best = None
                for tkt, leaf, wmem in root._waiters:
                    if not leaf._capacity_now(wmem):
                        continue
                    key = (-leaf.priority,
                           leaf._running / max(leaf.scheduling_weight, 1),
                           tkt)
                    if best is None or key < best[0]:
                        best = (key, tkt, leaf)
                return best is not None and best[1] == me[0]

            try:
                while not my_turn():
                    remaining = None if deadline is None \
                        else deadline - time.time()
                    if remaining is not None and remaining <= 0:
                        raise QueryRejected(
                            f"query queued in {self.name!r} longer than "
                            f"{timeout}s")
                    self._cv.wait(remaining)
            finally:
                root._waiters.remove(me)
                for g in self._chain():
                    g._queued -= 1
                # our departure (admitted OR timed out) can unblock a
                # differently-shaped waiter
                self._cv.notify_all()
            for g in self._chain():
                g._running += 1
                g._mem_used += mem

    def release(self, mem: int = 0):
        with self._cv:
            for g in self._chain():
                g._running -= 1
                g._mem_used -= mem
            # notify_all, not notify: a waiter that times out may have
            # just consumed the single notify without taking the slot,
            # which would leave another queued waiter blocked forever.
            self._cv.notify_all()


# the latency-class taxonomy (admission-to-SLO): interactive point
# lookups preempt dashboard refreshes preempt batch scans. Limits are
# per-class concurrency + queue depth; the shared root caps the tree.
LATENCY_CLASSES = ("interactive", "dashboard", "batch")


def latency_class_groups(root_concurrency: int = 64,
                         root_queued: int = 1024) -> ResourceGroup:
    """The default latency-class resource-group tree: a ``global``
    root bounding total admission, with interactive/dashboard/batch
    leaves whose priority + weight implement admission preemption
    (interactive first) and whose per-class limits keep one class from
    starving the others' queues."""
    root = ResourceGroup("global",
                         hard_concurrency_limit=root_concurrency,
                         max_queued=root_queued)
    root.add_child(ResourceGroup(
        "interactive", hard_concurrency_limit=root_concurrency,
        max_queued=root_queued, scheduling_weight=8, priority=2))
    root.add_child(ResourceGroup(
        "dashboard", hard_concurrency_limit=max(root_concurrency // 2, 1),
        max_queued=max(root_queued // 2, 1), scheduling_weight=4,
        priority=1))
    root.add_child(ResourceGroup(
        "batch", hard_concurrency_limit=max(root_concurrency // 16, 1),
        max_queued=max(root_queued // 16, 1), scheduling_weight=1,
        priority=0))
    return root


def latency_class_selector(session: Dict) -> str:
    """Route on the ``latency_class`` session property: a class name
    maps under the global tree, an explicit dotted path passes
    through, absent/empty lands on the root group."""
    lc = str((session or {}).get("latency_class", "") or "")
    if lc in LATENCY_CLASSES:
        return f"global.{lc}"
    return lc or "global"


class Dispatcher:
    """DispatchManager analog: select a group, admit, execute, account.

    `executor(query_id, query)` does the actual work (the coordinator's
    execute or a local run_query closure); the dispatcher owns only
    admission and lifecycle events."""

    def __init__(self, groups: Optional[List[ResourceGroup]] = None,
                 selector: Optional[Callable[[Dict], str]] = None,
                 resource_manager_url: Optional[str] = None,
                 coordinator_id: Optional[str] = None,
                 cluster_limits: Optional[Dict[str, int]] = None):
        """`resource_manager_url` + `cluster_limits` ({group path:
        cluster-wide hard concurrency}) enforce limits ACROSS
        coordinators: admission consults the resource manager's
        aggregated view and waits while other coordinators hold the
        cluster's slots (resourcemanager/ multi-coordinator
        arbitration)."""
        # register every group in each tree under its dotted path, so
        # selectors can target leaves ("etl.nightly") or roots ("etl")
        self.groups: Dict[str, ResourceGroup] = {}
        for root in (groups or [ResourceGroup("global")]):
            self._register(root, root.name)
        self._selector = selector or (lambda session: "global")
        self.resource_manager_url = resource_manager_url
        self.coordinator_id = coordinator_id or f"coord-{id(self):x}"
        self.cluster_limits = dict(cluster_limits or {})

    @classmethod
    def with_latency_classes(cls, root_concurrency: int = 64,
                             root_queued: int = 1024,
                             **kwargs) -> "Dispatcher":
        """A dispatcher admitting through the latency-class tree
        (interactive/dashboard/batch under one global root), routed by
        the ``latency_class`` session property -- the admission-to-SLO
        configuration scripts/loadgen.py drives."""
        return cls(groups=[latency_class_groups(root_concurrency,
                                                root_queued)],
                   selector=latency_class_selector, **kwargs)

    def _register(self, g: ResourceGroup, path: str):
        self.groups[path] = g
        self.groups.setdefault(g.name, g)
        for c in g.children.values():
            self._register(c, f"{path}.{c.name}")

    def select_group(self, session: Optional[Dict] = None) -> str:
        """The group path the selector routes this session to (public:
        the statement tier records it per query for system.queries)."""
        return self._selector(session or {})

    def _await_cluster_slot(self, group_name: str, group: ResourceGroup,
                            deadline: Optional[float]) -> None:
        """Cluster-wide admission gate: while OTHER coordinators'
        running queries leave no room under a cluster limit configured
        on the selected group OR ANY ANCESTOR path (local admission
        enforces the whole chain; so does this gate), wait (bounded
        poll; the reference long-polls the RM the same way). RM
        unreachable = fail open to local-only admission (availability
        over global strictness, the reference's degraded mode)."""
        if self.resource_manager_url is None:
            return
        parts = group_name.split(".")
        gates = []
        for i in range(len(parts)):
            prefix = ".".join(parts[:i + 1])
            limit = self.cluster_limits.get(prefix)
            if limit is not None and prefix in self.groups:
                gates.append((prefix, limit, self.groups[prefix]))
        if not gates:
            return
        from .resource_manager import remote_group_load
        while True:
            try:
                blocked = None
                for prefix, limit, g in gates:
                    remote = remote_group_load(self.resource_manager_url,
                                               prefix,
                                               self.coordinator_id)
                    if remote + g.stats()["running"] >= limit:
                        blocked = (prefix, limit)
                        break
            except Exception as e:  # noqa: BLE001 - RM down: degrade
                # to local-only admission, but count it -- a flapping
                # RM silently disabling cluster limits is an outage
                from .metrics import record_suppressed
                record_suppressed("dispatcher", "rm_gate", e)
                return
            if blocked is None:
                return
            if deadline is not None and time.time() >= deadline:
                raise QueryRejected(
                    f"cluster limit {blocked[1]} for group "
                    f"{blocked[0]!r} held by other coordinators")
            time.sleep(0.05)

    def group_stats(self) -> Dict[str, Dict[str, int]]:
        return {name: g.stats() for name, g in self.groups.items()
                if "." in name or not g.parent}

    def submit(self, executor: Callable[[str], object],
               session: Optional[Dict] = None,
               query_text: str = "",
               queue_timeout: Optional[float] = None,
               query_id: Optional[str] = None):
        """Admit + run one query synchronously (the reference's async
        dispatch is its HTTP shell; the admission semantics live here).
        Raises QueryRejected when the group's queue is full. The caller
        may supply the query id (the statement resource mints ids at
        POST time, before admission, like QueuedStatementResource)."""
        session = session or {}
        group_name = self._selector(session)
        group = self.groups.get(group_name)
        if group is None:
            raise QueryRejected(f"no resource group {group_name!r}")
        query_id = query_id or f"q-{uuid.uuid4().hex[:12]}"
        events = event_listeners()
        events.query_created(query_id, query_text,
                             session.get("user", ""))
        if failpoints.ARMED:
            # delay = a stalled dispatch ahead of the resource-group
            # queue, error = failed admission (the query fails cleanly
            # before holding any slot)
            failpoints.hit("dispatcher.admit")
        mem = 0
        if "query_max_memory" in session:
            from ..utils.config import parse_size
            mem = parse_size(session["query_max_memory"])
        # ONE admission deadline covers the cluster gate AND the local
        # queue wait (the caller's bound, not 2x it)
        deadline = None if queue_timeout is None \
            else time.time() + queue_timeout
        t_queue0 = time.time()
        try:
            self._await_cluster_slot(group_name, group, deadline)
            remaining = None if deadline is None \
                else max(deadline - time.time(), 0.001)
            group.acquire(remaining, mem=mem)
        finally:
            # queue-wait distribution (previously timed by NOBODY): the
            # cluster gate + local slot wait, rejected waits included --
            # a full queue's p99 is exactly the signal this exists for.
            # Labeled by resource group so loadgen p99s are
            # attributable per latency class.
            from .metrics import observe_histogram
            observe_histogram("presto_tpu_dispatch_queue_wait_seconds",
                              time.time() - t_queue0,
                              labels={"group": group_name})
        t0 = time.time()
        try:
            result = executor(query_id)
        except Exception as e:
            events.query_completed(query_id, "FAILED",
                                   wall_s=time.time() - t0, error=str(e))
            raise
        finally:
            group.release(mem=mem)
        rows = getattr(result, "row_count", 0)
        events.query_completed(query_id, "FINISHED", rows=rows,
                               wall_s=time.time() - t0)
        return result
