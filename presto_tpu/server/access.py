"""Access control: who may read/write which catalog/table/column.

Reference surface: presto-main-base/.../security/AccessControlManager.java
(checkCanSelectFromColumns / checkCanInsertIntoTable / ... called at
analysis time) and the file-based system access control
(presto-spi/.../security/SystemAccessControl.java + the rules-file
plugin). This engine checks at PLAN time -- the runner walks the plan's
scans and write targets before anything executes, the same boundary the
reference's analyzer checks sit on.

Rules evaluate top-down, FIRST MATCH wins (the reference's file rules
semantics); with no rules configured everything is allowed. A rule:

    {"user": "bob|analyst_.*",       # regex, default ".*"
     "catalog": "tpch",              # regex, default ".*"
     "table": "lineitem|orders",     # regex, default ".*"
     "columns": ["comment"],         # optional: restrict to these
     "privileges": ["SELECT"]}       # subset of SELECT/INSERT/DELETE/
                                     # UPDATE/CREATE/DROP; [] = deny

The manager is process-global (set_access_control) so every front door
(sql(), statement server, worker) enforces the same policy; servers may
also scope their own instance.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional, Sequence

from ..utils.locks import OrderedLock

__all__ = ["AccessDeniedException", "AccessControlManager",
           "set_access_control", "get_access_control"]

_PRIVILEGES = ("SELECT", "INSERT", "DELETE", "UPDATE", "CREATE", "DROP")


class AccessDeniedException(PermissionError):
    """The reference's ACCESS_DENIED error class."""


class AccessControlManager:
    def __init__(self, rules: Optional[List[Dict]] = None):
        self.rules = []
        for r in rules or []:
            self.rules.append({
                "user": re.compile(r.get("user", ".*") + r"\Z"),
                "catalog": re.compile(r.get("catalog", ".*") + r"\Z"),
                "table": re.compile(r.get("table", ".*") + r"\Z"),
                "columns": r.get("columns"),
                "privileges": {p.upper() for p in r.get("privileges", [])},
            })

    # -- rule evaluation ---------------------------------------------------

    def _allowed(self, user: str, catalog: str, table: str,
                 privilege: str, column: Optional[str] = None) -> bool:
        if not self.rules:
            return True
        for r in self.rules:
            if not r["user"].match(user or ""):
                continue
            if not r["catalog"].match(catalog):
                continue
            if not r["table"].match(table):
                continue
            # the first (user, catalog, table) match DECIDES: a rule's
            # column list restricts within that rule, it does not fall
            # through to later rules (file-rules semantics)
            if privilege not in r["privileges"]:
                return False
            if column is not None and r["columns"] is not None:
                return column in r["columns"]
            return True
        return False  # rules configured but none matched: deny

    def _check(self, user, catalog, table, privilege, columns=()):
        if not self._allowed(user, catalog, table, privilege):
            raise AccessDeniedException(
                f"Access Denied: Cannot {privilege.lower()} "
                f"{catalog}.{table} (user {user!r})")
        for c in columns or ():
            if not self._allowed(user, catalog, table, privilege, c):
                raise AccessDeniedException(
                    f"Access Denied: Cannot {privilege.lower()} column "
                    f"{c!r} of {catalog}.{table} (user {user!r})")

    # -- the analysis-time checks (AccessControl SPI names) ---------------

    def check_can_select_from_columns(self, user, catalog, table, columns):
        self._check(user, catalog, table, "SELECT", columns)

    def check_can_insert_into_table(self, user, catalog, table):
        self._check(user, catalog, table, "INSERT")

    def check_can_delete_from_table(self, user, catalog, table):
        self._check(user, catalog, table, "DELETE")

    def check_can_update_table(self, user, catalog, table):
        self._check(user, catalog, table, "UPDATE")

    def check_can_create_table(self, user, catalog, table):
        self._check(user, catalog, table, "CREATE")

    def check_can_drop_table(self, user, catalog, table):
        self._check(user, catalog, table, "DROP")

    # -- plan-walk enforcement --------------------------------------------

    def check_plan(self, root, user: str) -> None:
        """Walk a plan tree; every TableScanNode must pass the SELECT
        check with its referenced columns, every write node its write
        check (the runner calls this before execution)."""
        from ..plan import nodes as N
        seen = set()

        def walk(n):
            if id(n) in seen:
                return
            seen.add(id(n))
            if isinstance(n, N.TableScanNode):
                self.check_can_select_from_columns(
                    user, n.connector, n.table, n.columns)
            elif isinstance(n, N.TableFinishNode):
                if n.create:
                    self.check_can_create_table(user, n.connector, n.table)
                else:
                    self.check_can_insert_into_table(user, n.connector,
                                                     n.table)
            elif isinstance(n, N.TableRewriteNode):
                if n.kind == "delete":
                    self.check_can_delete_from_table(user, n.connector,
                                                     n.table)
                else:
                    self.check_can_update_table(user, n.connector, n.table)
            elif isinstance(n, N.DdlNode) and n.op == "drop_table":
                self.check_can_drop_table(user, n.connector, n.table)
            for s in n.sources:
                walk(s)

        walk(root)


_lock = OrderedLock("access._lock")
_manager: Optional[AccessControlManager] = None


def set_access_control(rules_or_manager) -> None:
    """Install the process-global policy (None clears it = allow all)."""
    global _manager
    with _lock:
        if rules_or_manager is None or \
                isinstance(rules_or_manager, AccessControlManager):
            _manager = rules_or_manager
        else:
            _manager = AccessControlManager(rules_or_manager)


def get_access_control() -> Optional[AccessControlManager]:
    return _manager
