"""Session property manager: rule-based per-user/source defaults.

Reference surface: the SessionPropertyConfigurationManager SPI and its
file/db plugins (presto-file-session-property-manager /
presto-db-session-property-manager,
AbstractSessionPropertyManager) -- rules matched on user/source apply
session-property DEFAULTS at query start; explicit client values always
win. Rules evaluate in order and MERGE (later matches override earlier
defaults, the reference's file-manager semantics):

    set_session_property_manager(SessionPropertyManager([
        {"user": "etl_.*", "properties": {"query_max_memory": "24GB"}},
        {"source": "dashboard", "properties": {"sf": "0.01"}},
    ]))
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional

from ..utils.locks import OrderedLock

__all__ = ["SessionPropertyManager", "set_session_property_manager",
           "get_session_property_manager"]


class SessionPropertyManager:
    def __init__(self, rules: Optional[List[Dict]] = None):
        self.rules = []
        for r in rules or []:
            self.rules.append({
                "user": re.compile(r.get("user", ".*") + r"\Z"),
                "source": re.compile(r.get("source", ".*") + r"\Z"),
                "clientTags": set(r.get("clientTags", [])),
                "properties": dict(r.get("properties", {})),
            })

    def defaults_for(self, user: str, source: str = "",
                     client_tags: Optional[List[str]] = None) -> Dict:
        out: Dict = {}
        tags = set(client_tags or [])
        for r in self.rules:
            if not r["user"].match(user or ""):
                continue
            if not r["source"].match(source or ""):
                continue
            if r["clientTags"] and not r["clientTags"] <= tags:
                continue
            out.update(r["properties"])
        return out


_lock = OrderedLock("session_properties._lock")
_manager: Optional[SessionPropertyManager] = None


def set_session_property_manager(mgr) -> None:
    global _manager
    with _lock:
        if mgr is None or isinstance(mgr, SessionPropertyManager):
            _manager = mgr
        else:
            _manager = SessionPropertyManager(mgr)


def get_session_property_manager() -> Optional[SessionPropertyManager]:
    return _manager
