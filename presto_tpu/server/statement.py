"""Client statement protocol: the coordinator's POST /v1/statement seam.

Reference surface: the REST protocol every Presto client speaks --
QueuedStatementResource (presto-main/.../server/protocol/
QueuedStatementResource.java:210 `POST /v1/statement` -> QueryResults
with a `nextUri` into the queued resource, redirecting to
ExecutingStatementResource once dispatch completes) and
StatementClientV1 (presto-client/.../StatementClientV1.java:88,365 --
advance() polls nextUri until it disappears). Response documents carry
{id, infoUri, nextUri, partialCancelUri, columns, data, stats, error,
updateType}; session mutations ride response headers
(X-Presto-Set-Session / X-Presto-Started-Transaction-Id / ...).

This server fronts the engine: queries admit through the Dispatcher
(resource groups + events), transact through the TransactionManager,
progress through a QueryStateMachine (query_state.py), and execute on a
background thread -- the LocalDispatchQuery.startWaitingForPrerequisites
-> SqlQueryExecution.start pipeline condensed to one process. Results
page out `page_rows` rows per nextUri hop, values rendered with the
reference's JSON conventions (decimals/dates/timestamps as strings).
"""

from __future__ import annotations

import json
import re
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import failpoints
from .. import types as T
from ..transaction import TransactionManager
from ..utils.locks import OrderedLock
from .dispatcher import Dispatcher, QueryRejected
from .flight_recorder import get_flight_recorder, record_event
from .query_state import QueryState, QueryStateMachine, TERMINAL_STATES
from .tracing import TraceContext, new_span_id

__all__ = ["StatementServer", "render_value"]


def render_value(v, null: bool, ty: T.Type):
    """Engine-native value -> client JSON (the reference's column
    rendering: decimals and temporals as strings)."""
    if null or v is None:
        return None
    if ty.is_decimal:
        s = ty.scale
        v = int(v)
        if s == 0:
            return str(v)
        sign = "-" if v < 0 else ""
        a = abs(v)
        return f"{sign}{a // 10**s}.{a % 10**s:0{s}d}"
    if ty.base == "date":
        return str(np.datetime64("1970-01-01") + int(v))
    if ty.base == "timestamp":
        us = int(v)
        base = np.datetime64("1970-01-01T00:00:00") + np.timedelta64(us, "us")
        return str(base).replace("T", " ")
    if ty.base == "array":
        return [render_value(e, e is None, ty.element_type) for e in v]
    if ty.is_floating:
        return float(v)
    if ty.base == "boolean":
        return bool(v)
    if ty.is_integral:
        return int(v)
    return str(v)


_ERROR_CODES = {
    "SYNTAX_ERROR": (1, "USER_ERROR"),
    "USER_CANCELED": (20000, "USER_ERROR"),
    "QUERY_QUEUE_FULL": (131075, "INSUFFICIENT_RESOURCES"),
    "GENERIC_INTERNAL_ERROR": (65536, "INTERNAL_ERROR"),
}


def _max_q_error_of(query_id: str):
    """Worst finalized q-error for one query id, or None (pre-close
    and on any registry hiccup -- a cluster frame must never fail on
    its garnish)."""
    try:
        from ..exec.accuracy import query_max_q_error
        q = query_max_q_error(query_id)
        return round(q, 2) if q is not None else None
    except Exception:  # noqa: BLE001
        return None


def _error_doc(name: str, message: str) -> dict:
    code, etype = _ERROR_CODES.get(name, _ERROR_CODES["GENERIC_INTERNAL_ERROR"])
    return {"message": message, "errorCode": code, "errorName": name,
            "errorType": etype,
            "failureInfo": {"type": name, "message": message}}


class _Query:
    """One statement's server-side lifecycle + result store."""

    def __init__(self, query_id: str, slug: str, text: str,
                 session_values: Dict, user: str, txn_id: Optional[str],
                 client_ctx: Optional[TraceContext] = None):
        self.id = query_id
        self.slug = slug
        self.text = text
        self.session_values = session_values
        self.user = user
        self.txn_id = txn_id
        self.machine = QueryStateMachine(query_id)
        # this query's trace identity: the client's propagated trace id
        # when an X-Presto-Trace header arrived, else the query id
        # itself (so GET /v1/trace/{queryId} resolves without a lookup
        # table); span_id is the query ROOT span every other span of
        # the query ultimately parents to
        self.trace_ctx = TraceContext(
            client_ctx.trace_id if client_ctx else query_id,
            new_span_id())
        self.client_parent = client_ctx.span_id if client_ctx else None
        self.columns: Optional[List[dict]] = None
        self.rows: List[list] = []
        # client result-drain window (the trace's "client fetch" leg):
        # set by the executing resource, read once at final-page serve
        self.first_fetch_at: Optional[float] = None
        self.fetch_span_done = False
        self.update_type: Optional[str] = None
        self.update_count: Optional[int] = None
        # structured execution stats (QueryStats) once the engine ran
        self.result_stats = None
        # client-visible progress high-water marks: the live registry's
        # per-task aggregate can transiently dip when the task set
        # changes (a new task joins at 0%), but the PROTOCOL promises
        # monotonically non-decreasing progress on every poll -- the
        # max is taken here, per query (benign last-writer race: both
        # writers only raise it)
        self.progress_hwm = {"pct": 0.0, "rows": 0, "bytes": 0,
                             "peak": 0}
        # response-header mutations for the client to apply
        self.set_session: Dict[str, str] = {}
        self.started_txn: Optional[str] = None
        self.clear_txn: bool = False
        # admission attribution: the resource group the dispatcher
        # routed this query to, and (after execution) the size of the
        # batched dispatch that served it (0 = serial)
        self.resource_group: str = ""
        self.batch_size: int = 0


_SESSION_STMT = re.compile(
    r"\s*(start\s+transaction|commit|rollback|set\s+session)\b",
    re.IGNORECASE)


class StatementServer:
    """Coordinator statement resource over the local engine (or any
    executor callable). `executor(text, session_values, query_id,
    txn_id)` returns an object with .rows()/.names/.types (QueryResult);
    default executes through the SQL front door."""

    # request-handler threads share the query registry and the metrics
    # roll-ups; writes go through these locks (tpulint C001)
    _GUARDED_BY = {"_qlock": ("_queries",),
                   "_metrics_lock": ("_queries_by_state", "_totals",
                                     "_workers_alive",
                                     "_workers_draining")}

    def __init__(self, port: int = 0, sf: float = 0.01,
                 dispatcher: Optional[Dispatcher] = None,
                 executor=None, page_rows: int = 1024,
                 queue_poll_s: float = 1.0,
                 query_ttl_s: float = 600.0,
                 tls: Optional[tuple] = None,
                 profile_workers=None):
        """`profile_workers`: worker base URLs (list, or zero-arg
        callable returning one) whose GET /v1/profile slices the
        cluster-merged GET /v1/profile on THIS server folds in --
        wire the coordinator's worker view here on the distributed
        tier; None serves this process's slice alone."""
        self.sf = sf
        self._profile_workers = profile_workers
        # structured log correlation: every engine log record carries
        # the ambient trace/query ids from here on (utils/log.py)
        from ..utils.log import ensure_log_context
        ensure_log_context()
        from ..sql.statements import PreparedStatements
        # per-user registries (the reference scopes prepared statements
        # per session via X-Presto-Prepared-Statement headers)
        self._prepared: Dict[str, PreparedStatements] = {}
        self.page_rows = page_rows
        self.queue_poll_s = queue_poll_s
        self.query_ttl_s = query_ttl_s
        self.dispatcher = dispatcher or Dispatcher()
        self.transactions = TransactionManager()
        self._executor = executor or self._default_executor
        self._queries: Dict[str, _Query] = {}
        self._qlock = OrderedLock("statement.StatementServer._qlock")
        self._started_at = time.time()
        # lifetime roll-ups for /v1/metrics (terminal queries only;
        # accounted exactly once per query in _run's finally)
        self._metrics_lock = OrderedLock("statement.StatementServer._metrics_lock")
        self._queries_by_state: Dict[str, int] = {}
        self._totals = {"rows": 0, "bytes": 0, "wall_us": 0,
                        "compile_us": 0, "execute_us": 0,
                        "peak_memory_bytes": 0}
        # fleet liveness cache: refreshed by every /v1/cluster probe;
        # None = never probed (the gauge then reports the configured
        # count optimistically rather than paying an HTTP probe per
        # metrics scrape)
        self._workers_alive: Optional[int] = None
        self._workers_draining = 0  # DRAINING rows of the last probe
        # stuck-progress watchdog (server/watchdog.py): scans live
        # queries; per query disabled unless stuck_query_threshold_ms /
        # PRESTO_TPU_STUCK_MS arms a threshold
        from .watchdog import StuckProgressWatchdog
        self._watchdog = StuckProgressWatchdog(
            self._stuck_candidates, tier="statement")
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        scheme = "http"
        if tls is not None:
            from .tls import server_context
            self._httpd.socket = server_context(*tls).wrap_socket(
                self._httpd.socket, server_side=True)
            scheme = "https"
        self.port = self._httpd.server_address[1]
        self.url = f"{scheme}://127.0.0.1:{self.port}"
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------

    def start(self):
        from ..connectors.system import register_statement_server
        register_statement_server(self)  # system.queries introspection
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        self._watchdog.start()
        return self

    def stop(self):
        self._watchdog.stop()
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- execution ------------------------------------------------------

    def _default_executor(self, text: str, session_values: Dict,
                          query_id: str, txn_id: Optional[str]):
        from ..sql import sql as run_sql
        from ..sql.statements import preprocess
        sf = float(session_values.get("sf", self.sf))
        kwargs = {}
        if "max_groups" in session_values:
            kwargs["max_groups"] = int(session_values["max_groups"])
        if "join_capacity" in session_values:
            kwargs["join_capacity"] = int(session_values["join_capacity"])
        # SHOW/DESCRIBE rewrites + per-server prepared statements (the
        # coordinator session analog of X-Presto-Prepared-Statement)
        from ..sql.statements import PreparedStatements
        user = self._user_of(query_id)
        pre = preprocess(text, catalog=session_values.get("catalog", "tpch"),
                         prepared=self._prepared.setdefault(
                             user, PreparedStatements()))
        if pre.ack is not None:
            from ..exec.runner import QueryResult
            return QueryResult([], [], [pre.ack], 0)
        kwargs["session"] = dict(session_values)
        kwargs["session"].setdefault("user", user)
        # the engine's stage spans must land under THIS query's trace
        # (same id _emit_trace uses for the state spans -> one trace
        # per query, and no shared default-"query" trace growing forever)
        kwargs["query_id"] = query_id
        ctx = self._trace_ctx_of(query_id)
        if ctx is not None:
            # stage spans become children of the query root span
            kwargs["trace_id"] = ctx
        # concurrent-query batching (exec/batching.py): co-batchable
        # statements that form a batch are served by ONE vmapped
        # dispatch and return here; everything else (not batchable,
        # batching off, no batch formed) runs the serial path below
        from ..exec.batching import get_batching_executor
        res = get_batching_executor().try_execute(
            pre.text, sf=sf, session=kwargs["session"],
            query_id=query_id, trace_id=kwargs.get("trace_id"),
            max_groups=kwargs.get("max_groups"),
            join_capacity=kwargs.get("join_capacity"),
            catalog=session_values.get("catalog", "tpch"))
        if res is not None:
            return res
        return run_sql(pre.text, sf=sf, **kwargs)

    def _user_of(self, query_id: str) -> str:
        with self._qlock:
            q = self._queries.get(query_id)
        return q.user if q is not None else ""

    def _trace_ctx_of(self, query_id: str) -> Optional[TraceContext]:
        with self._qlock:
            q = self._queries.get(query_id)
        return q.trace_ctx if q is not None else None

    def _emit_trace(self, q: "_Query") -> None:
        """Terminal-state hook: the query ROOT span (queued->terminal)
        plus per-state child spans (QueryStateTracingListener analog).
        Everything the query recorded elsewhere -- engine stage spans,
        coordinator/worker spans on the distributed tier -- parents
        into this root, so GET /v1/trace/{queryId} serves ONE tree."""
        from .tracing import emit_span, get_tracer, \
            spans_from_state_timings
        if get_tracer() is None:
            return
        try:
            timings = q.machine.timings()
            start = timings.get(QueryState.QUEUED, time.time())
            end = timings.get(q.machine.state, time.time())
            emit_span(q.trace_ctx.trace_id, "query", start, end,
                      {"queryId": q.id, "user": q.user,
                       "state": q.machine.state,
                       "query": q.text[:200]},
                      span_id=q.trace_ctx.span_id,
                      parent_id=q.client_parent)
            spans_from_state_timings(
                q.trace_ctx.trace_id, timings,
                ["QUEUED", "PLANNING", "RUNNING", "FINISHING",
                 "FINISHED", "FAILED"],
                {"user": q.user},
                parent_id=q.trace_ctx.span_id)
        except Exception as e:  # noqa: BLE001 - tracing must never
            # fail a query, but a tracer that stops shipping spans
            # should show on /v1/metrics
            from .metrics import record_suppressed
            record_suppressed("statement", "trace_spans", e)

    def _reap_locked(self) -> None:
        """Drop terminal queries (and their materialized result rows)
        older than query_ttl_s -- QueryTracker's expiration (the worker
        side reaps tasks the same way)."""
        import time as _time
        cutoff = _time.time() - self.query_ttl_s
        for qid in [qid for qid, q in self._queries.items()
                    if q.machine.is_done()
                    and q.machine.timings().get(q.machine.state, 0) < cutoff]:
            del self._queries[qid]

    def create_query(self, text: str, user: str,
                     session_values: Dict, txn_id: Optional[str],
                     client_ctx: Optional[TraceContext] = None) -> _Query:
        # rule-based session defaults (SessionPropertyConfigurationManager
        # analog): manager defaults under, client values over
        from .session_properties import get_session_property_manager
        mgr = get_session_property_manager()
        if mgr is not None:
            session_values = {**mgr.defaults_for(
                user, session_values.get("source", ""),
                session_values.get("clientTags")), **session_values}
        q = _Query(f"20260730_{uuid.uuid4().hex[:12]}",
                   uuid.uuid4().hex[:12], text, session_values, user,
                   txn_id, client_ctx=client_ctx)
        # every state transition lands on the flight-recorder timeline
        # (the ring a slow/failed dump replays)
        q.machine.add_listener(
            lambda old, new, qid=q.id: record_event(
                "query_state", query_id=qid, frm=old, to=new))
        with self._qlock:
            self._reap_locked()
            self._queries[q.id] = q
        threading.Thread(target=self._run, args=(q,), daemon=True).start()
        return q

    def inflight_doc(self) -> List[dict]:
        """In-flight statement manifest (one entry per non-terminal
        query): what a ClusterStateSender heartbeats to the resource
        manager so a StandbyCoordinator can adopt these statements
        when this coordinator's heartbeat lapses."""
        with self._qlock:
            queries = list(self._queries.values())
        out = []
        for q in queries:
            state = q.machine.state
            if state in TERMINAL_STATES:
                continue
            out.append({"queryId": q.id, "slug": q.slug,
                        "query": q.text, "user": q.user, "state": state,
                        "sessionProperties": q.session_values})
        return out

    def adopt_query(self, query_id: str, slug: str, text: str,
                    user: str, session_values: Dict) -> _Query:
        """Failover adoption: run `text` on THIS server under the
        ORIGINAL query id + slug, so a client re-resolving its polls
        here (via the router, or the standby url) drains the same
        statement. Idempotent per query id -- a re-fired failover
        never double-runs an adopted statement."""
        q = _Query(query_id, slug, text, dict(session_values or {}),
                   user, None)
        with self._qlock:
            existing = self._queries.get(query_id)
            if existing is not None:
                return existing
            self._reap_locked()
            self._queries[query_id] = q
        record_event("query_adopt", query_id=query_id, user=user)
        q.machine.add_listener(
            lambda old, new, qid=q.id: record_event(
                "query_state", query_id=qid, frm=old, to=new))
        threading.Thread(target=self._run, args=(q,), daemon=True).start()
        return q

    def _run(self, q: _Query):
        try:
            self._run_inner(q)
        finally:
            if q.machine.is_done():
                self._emit_trace(q)
                self._account_query(q)
                self._maybe_flight_dump(q)
                self._record_history(q)

    def _slow_threshold_ms(self, q: _Query) -> float:
        """slow_query_threshold_ms session property, env fallback
        PRESTO_TPU_SLOW_QUERY_MS; 0 / unset disables slow dumps."""
        import os
        raw = q.session_values.get(
            "slow_query_threshold_ms",
            os.environ.get("PRESTO_TPU_SLOW_QUERY_MS", "0"))
        try:
            return float(raw)
        except (TypeError, ValueError):
            return 0.0

    def _maybe_flight_dump(self, q: _Query) -> None:
        """Auto-dump the flight-recorder ring for a failed or slow
        query -- exactly once per query (the recorder dedups by key),
        counted per reason on /v1/metrics. Never fails the query."""
        try:
            state = q.machine.state
            reason = None
            if state == QueryState.FAILED:
                reason = "failed"
            else:
                thresh = self._slow_threshold_ms(q)
                if thresh > 0 and q.machine.elapsed_ms() >= thresh:
                    reason = "slow"
            if reason is None:
                return
            get_flight_recorder().maybe_dump(
                q.id, reason,
                extra={"state": state, "user": q.user,
                       "elapsedMs": q.machine.elapsed_ms(),
                       "traceId": q.trace_ctx.trace_id,
                       "query": q.text[:200]})
        except Exception as e:  # noqa: BLE001 - a dump problem is
            # telemetry loss, not a query failure; leave a counted trace
            from .metrics import record_suppressed
            record_suppressed("statement", "flight_dump", e)

    def _record_history(self, q: _Query) -> None:
        """Archive one terminal query into the process history archive
        (server/history.py) -- the record the perf sentinel gates and
        GET /v1/history / system.query_history serve. Runs AFTER the
        flight-dump check so a failed/slow dump wins the per-query dump
        slot over a perf-regression dump. Never fails the query."""
        try:
            from .history import QueryHistoryArchive, get_history_archive
            # the EFFECTIVE scale factor salts the sentinel fingerprint:
            # the server-constructor sf applies when the client set no
            # session property, and cross-sf runs of the same SQL must
            # not share a baseline (a workload change is not a
            # regression)
            session = dict(q.session_values)
            session.setdefault("sf", self.sf)
            record = QueryHistoryArchive.record_of(
                q.id, q.machine.state, q.user, q.text,
                q.machine.elapsed_ms(), q.trace_ctx.trace_id,
                query_stats=q.result_stats, session=session)
            # the batch-template fingerprint (exec/batching.py) rides
            # the record so the archive's per-fingerprint frequency
            # can drive batch-formation windows across restarts
            from ..exec.batching import template_fp_of
            bfp = template_fp_of(q.id)
            if bfp:
                record["batchFingerprint"] = bfp
                record["batchSize"] = q.batch_size
            get_history_archive().add(record)
        except Exception as e:  # noqa: BLE001 - history is telemetry;
            # a malformed executor result (query_stats of a foreign
            # type) must not kill the query thread's terminal path
            from .metrics import record_suppressed
            record_suppressed("statement", "record_history", e)

    def _account_query(self, q: _Query) -> None:
        """Roll a terminal query into the /v1/metrics lifetime totals
        (exactly once: _run's finally is the single terminal seam) and
        feed the latency distributions: end-to-end wall plus one
        observation per traversed state, exemplar'd with the query's
        trace id so a p99 bucket links straight to its waterfall."""
        from .metrics import observe_histogram
        tid = q.trace_ctx.trace_id
        observe_histogram("presto_tpu_query_latency_seconds",
                          q.machine.elapsed_ms() / 1e3, trace_id=tid)
        timings = q.machine.timings()
        entered = sorted(((s, t) for s, t in timings.items()),
                         key=lambda x: x[1])
        for i, (state, start) in enumerate(entered):
            if state not in ("QUEUED", "PLANNING", "RUNNING",
                             "FINISHING"):
                continue
            end = entered[i + 1][1] if i + 1 < len(entered) \
                else time.time()
            observe_histogram("presto_tpu_query_state_seconds",
                              max(end - start, 0.0),
                              labels={"state": state}, trace_id=tid)
        qs = q.result_stats
        with self._metrics_lock:
            st = q.machine.state
            self._queries_by_state[st] = \
                self._queries_by_state.get(st, 0) + 1
            self._totals["rows"] += len(q.rows)
            self._totals["wall_us"] += q.machine.elapsed_ms() * 1000
            if qs is not None:
                self._totals["bytes"] += qs.output_bytes
                self._totals["compile_us"] += qs.compile_us
                self._totals["execute_us"] += qs.stage_us("execute")
                self._totals["peak_memory_bytes"] = max(
                    self._totals["peak_memory_bytes"],
                    qs.peak_memory_bytes)

    def _run_inner(self, q: _Query):
        m = _SESSION_STMT.match(q.text)
        try:
            if m:
                self._run_session_statement(q, m.group(1).lower())
                return
            # per-query failpoint schedule (`failpoints` session
            # property): armed for this query's dispatch + execution
            # scope, restored afterwards
            q.resource_group = self.dispatcher.select_group(
                {"user": q.user, **q.session_values})
            with failpoints.session_scope(
                    q.session_values.get("failpoints")):
                self.dispatcher.submit(
                    lambda qid: self._run_engine(q),
                    session={"user": q.user, **q.session_values},
                    query_text=q.text, query_id=q.id,
                    queue_timeout=float(q.session_values.get(
                        "queue_timeout_s", 60.0)))
        except QueryRejected as e:
            q.machine.to_failed(_error_doc("QUERY_QUEUE_FULL", str(e)))
        except Exception as e:  # noqa: BLE001
            name = "SYNTAX_ERROR" if "parse" in type(e).__name__.lower() \
                or "Syntax" in str(e) else "GENERIC_INTERNAL_ERROR"
            q.machine.to_failed(_error_doc(name, f"{type(e).__name__}: {e}"))

    def _run_engine(self, q: _Query):
        if failpoints.ARMED:
            # hang = a wedged statement tier (the client poll deadline's
            # test surface); error = a query failed before planning
            failpoints.hit("statement.execute")
        q.machine.to_planning()
        m = re.match(r"\s*explain(\s+analyze)?\b", q.text, re.IGNORECASE)
        if m:
            # EXPLAIN [ANALYZE]: one varchar plan-text column (the
            # reference's EXPLAIN output shape)
            from ..plan import explain as explain_plan
            from ..plan import explain_analyze
            from ..sql import plan_sql
            inner = q.text[m.end():].strip()
            sf = float(q.session_values.get("sf", self.sf))
            q.machine.to_running()
            text = explain_analyze(plan_sql(inner), sf=sf,
                                   session=q.session_values) \
                if m.group(1) \
                else explain_plan(plan_sql(inner), regions=True,
                                  session=q.session_values, sf=sf)
            q.columns = [{"name": "Query Plan", "type": "varchar"}]
            q.rows = [[line] for line in text.splitlines()]
            q.machine.to_finishing()
            q.machine.to_finished()
            return
        q.machine.to_running()
        if q.txn_id is not None:
            self.transactions.get(q.txn_id)  # validates + touches
            if re.match(r"\s*(insert|create\s+table|drop\s+table|delete|"
                        r"update)\b", q.text, re.IGNORECASE):
                # checkConnectorWrite: writes refuse READ ONLY txns
                self.transactions.access_check_write(q.txn_id, "memory")
            res = self._executor(q.text, q.session_values, q.id, q.txn_id)
        else:
            res = self.transactions.run_autocommit(
                lambda tid: self._executor(q.text, q.session_values, q.id,
                                           tid))
        q.machine.to_finishing()
        wm = re.match(r"\s*(insert|create\s+table|drop\s+table|delete|"
                      r"update)\b", q.text, re.IGNORECASE)
        if wm:
            kind = " ".join(wm.group(1).upper().split())
            q.update_type = {"INSERT": "INSERT",
                             "CREATE TABLE": "CREATE TABLE AS",
                             "DROP TABLE": "DROP TABLE",
                             "DELETE": "DELETE",
                             "UPDATE": "UPDATE"}[kind]
            if res.types and res.types[0].base == "bigint" and \
                    res.row_count == 1:
                q.update_count = int(res.columns[0][0])
        q.result_stats = getattr(res, "query_stats", None)
        from ..exec.batching import batch_size_of
        q.batch_size = batch_size_of(q.id)
        q.columns = [{"name": n, "type": str(t)}
                     for n, t in zip(res.names, res.types)]
        # M001: protocol rendering of the FINAL RESULT the client
        # asked for -- output cardinality, already materialized
        _BOUNDED_BY = {"rendered": "final result rows (protocol "
                                   "rendering)"}
        rendered = []
        for i in range(res.row_count):
            rendered.append([
                render_value(res.columns[c][i], bool(res.nulls[c][i]),
                             res.types[c])
                for c in range(len(res.types))])
        q.rows = rendered
        q.machine.to_finished()
        return res

    def _run_session_statement(self, q: _Query, kind: str):
        q.machine.to_planning()
        q.machine.to_running()
        kind = " ".join(kind.split())
        if kind == "start transaction":
            if q.txn_id is not None:
                raise RuntimeError("already in a transaction")
            read_only = bool(re.search(r"read\s+only", q.text, re.I))
            q.started_txn = self.transactions.begin(read_only=read_only)
            q.update_type = "START TRANSACTION"
        elif kind in ("commit", "rollback"):
            if q.txn_id is None:
                raise RuntimeError(f"{kind.upper()} outside a transaction")
            if kind == "commit":
                self.transactions.commit(q.txn_id)
            else:
                self.transactions.rollback(q.txn_id)
            q.clear_txn = True
            q.update_type = kind.upper()
        else:  # SET SESSION k = v
            m = re.match(r"\s*set\s+session\s+([A-Za-z_][\w.]*)\s*=\s*(.+?)\s*$",
                         q.text, re.IGNORECASE)
            if not m:
                raise ValueError(f"cannot parse SET SESSION: {q.text!r}")
            key, raw = m.group(1), m.group(2).strip().rstrip(";").strip()
            if raw.startswith("'") and raw.endswith("'"):
                raw = raw[1:-1]
            q.set_session[key] = raw
            q.update_type = "SET SESSION"
        q.columns = [{"name": "result", "type": "boolean"}]
        q.rows = [[True]]
        q.machine.to_finishing()
        q.machine.to_finished()

    # -- document assembly ---------------------------------------------

    def get_query(self, query_id: str, slug: str) -> Optional[_Query]:
        with self._qlock:
            q = self._queries.get(query_id)
        if q is None or q.slug != slug:
            return None
        return q

    def queued_doc(self, q: _Query, token: int) -> dict:
        state = q.machine.state
        doc = self._base_doc(q, state)
        if state == QueryState.QUEUED:
            doc["nextUri"] = \
                f"{self.url}/v1/statement/queued/{q.id}/{q.slug}/{token + 1}"
        elif state in (QueryState.FAILED, QueryState.CANCELED):
            doc["error"] = q.machine.error or \
                _error_doc("USER_CANCELED", "query was canceled")
        else:
            doc["nextUri"] = \
                f"{self.url}/v1/statement/executing/{q.id}/{q.slug}/0"
        return doc

    def executing_doc(self, q: _Query, token: int) -> dict:
        state = q.machine.state
        doc = self._base_doc(q, state)
        if state in (QueryState.FAILED, QueryState.CANCELED):
            doc["error"] = q.machine.error or \
                _error_doc("USER_CANCELED", "query was canceled")
            return doc
        if state != QueryState.FINISHED:
            # results not materialized yet: poll the same token
            doc["nextUri"] = \
                f"{self.url}/v1/statement/executing/{q.id}/{q.slug}/{token}"
            return doc
        doc["columns"] = q.columns
        if q.first_fetch_at is None:
            q.first_fetch_at = time.time()
        lo = token * self.page_rows
        hi = lo + self.page_rows
        page = q.rows[lo:hi]
        if page:
            doc["data"] = page
        if q.update_type:
            doc["updateType"] = q.update_type
        if q.update_count is not None:
            doc["updateCount"] = q.update_count
        if hi < len(q.rows):
            doc["nextUri"] = \
                f"{self.url}/v1/statement/executing/{q.id}/{q.slug}/{token + 1}"
        elif not q.fetch_span_done:
            # final page served: the client-drain leg of the trace
            # (first results poll -> last page out the door). The flag
            # check is best-effort: a concurrent re-drain could emit a
            # second span, acceptable for telemetry.
            q.fetch_span_done = True
            from .tracing import emit_span
            emit_span(q.trace_ctx.trace_id, "client.fetch",
                      q.first_fetch_at, time.time(),
                      {"rows": len(q.rows), "pages": token + 1},
                      parent_id=q.trace_ctx.span_id)
        return doc

    def _progress_doc(self, q: _Query) -> Optional[dict]:
        """The query's live progress aggregate: its own engine entry
        plus every remote task entry tagged with its trace id
        (exec/progress.py -- fed locally by run_query, cross-worker by
        the coordinator's status polls)."""
        from ..exec.progress import aggregate_query_progress
        return aggregate_query_progress({q.id, q.trace_ctx.trace_id})

    def _base_doc(self, q: _Query, state: str) -> dict:
        queued = state == QueryState.QUEUED
        doc = {
            "id": q.id,
            "infoUri": f"{self.url}/v1/query/{q.id}",
            "stats": {
                "state": state,
                "queued": queued,
                "scheduled": state not in (QueryState.QUEUED,
                                           QueryState.PLANNING),
                "elapsedTimeMillis": q.machine.elapsed_ms(),
                "processedRows": len(q.rows),
                "processedBytes": 0,
                "peakMemoryBytes": 0,
            },
        }
        stats = doc["stats"]
        prog = self._progress_doc(q)
        hwm = q.progress_hwm
        if prog is not None:
            # live heartbeats: an IN-FLIGHT poll sees real movement
            # (the round-1 protocol hardcoded zeros until FINISHED).
            # Counters clamp to the per-query high-water mark so the
            # client-visible sequence is monotonic even when the task
            # set changes under the aggregate.
            hwm["rows"] = max(hwm["rows"], prog["rows"])
            hwm["bytes"] = max(hwm["bytes"], prog["bytes"])
            hwm["peak"] = max(hwm["peak"], prog["peakMemoryBytes"])
            hwm["pct"] = max(hwm["pct"], prog["progressPercent"])
            stats["stage"] = prog["stage"]
            stats["lastAdvanceAgeMs"] = prog["lastAdvanceAgeMs"]
            stats["liveTasks"] = prog["runningTasks"]
            stats["splitsDone"] = prog["splitsDone"]
            stats["splitsPlanned"] = prog["splitsPlanned"]
        stats["processedRows"] = max(len(q.rows), hwm["rows"])
        stats["processedBytes"] = hwm["bytes"]
        stats["peakMemoryBytes"] = hwm["peak"]
        stats["progressPercent"] = 100.0 \
            if state == QueryState.FINISHED else round(hwm["pct"], 1)
        qs = q.result_stats
        if qs is not None:
            # the engine's structured stats populate the client
            # protocol's stats field (StatementStats analog), with the
            # full stage/operator document alongside for rich clients
            stats["processedBytes"] = max(stats["processedBytes"],
                                          qs.output_bytes)
            stats["peakMemoryBytes"] = max(stats["peakMemoryBytes"],
                                           qs.peak_memory_bytes)
            stats["compileTimeMicros"] = qs.compile_us
            stats["executeTimeMicros"] = qs.stage_us("execute")
            stats["queryStats"] = qs.to_json()
        return doc

    def cancel(self, q: _Query) -> None:
        q.machine.to_canceled()

    def admin_doc(self, query_id: str) -> Optional[dict]:
        with self._qlock:
            q = self._queries.get(query_id)
        if q is None:
            return None
        return {"queryId": q.id, "state": q.machine.state,
                "query": q.text, "user": q.user,
                "sessionProperties": q.session_values,
                "timings": q.machine.timings(),
                "elapsedTimeMillis": q.machine.elapsed_ms(),
                "errorInfo": q.machine.error,
                "resourceGroup": q.resource_group,
                "batchSize": q.batch_size,
                # the live-progress aggregate (None before anything
                # registered): system.queries' progress columns and the
                # per-query admin page read it mid-flight
                "progress": self._progress_doc(q),
                "queryStats": q.result_stats.to_json()
                if q.result_stats is not None else None}

    def queries_doc(self) -> List[dict]:
        with self._qlock:
            ids = list(self._queries)
        return [self.admin_doc(i) for i in ids]

    def trace_doc(self, query_or_trace_id: str) -> Optional[dict]:
        """The stitched one-trace-per-query document for GET
        /v1/trace/{queryId}. Accepts a query id (resolved to its trace
        id) or, for reaped queries, a raw trace id."""
        from .tracing import get_tracer, trace_doc_of
        with self._qlock:
            q = self._queries.get(query_or_trace_id)
        tid = q.trace_ctx.trace_id if q is not None else query_or_trace_id
        doc = trace_doc_of(get_tracer(), tid)
        if doc is not None and q is not None:
            doc["queryId"] = q.id
            doc["state"] = q.machine.state
        return doc

    def _stuck_candidates(self):
        """Live queries offered to the stuck-progress watchdog: every
        non-terminal query past QUEUED (queued waits are the
        dispatcher's business), threshold from its session (env
        fallback), last advance = the freshest of its state transitions
        and its progress entries' heartbeats -- so a query wedged
        before the engine registered anything still ages from the
        moment it entered RUNNING."""
        from ..exec.progress import aggregate_query_progress
        from .watchdog import StuckCandidate, resolve_stuck_threshold_ms
        with self._qlock:
            queries = list(self._queries.values())
        out = []
        now = time.time()
        for q in queries:
            state = q.machine.state
            if state == QueryState.QUEUED or state in TERMINAL_STATES:
                continue
            thr = resolve_stuck_threshold_ms(q.session_values)
            if thr <= 0:
                continue
            last = max(q.machine.timings().values())
            prog = aggregate_query_progress({q.id,
                                             q.trace_ctx.trace_id})
            if prog is not None:
                last = max(last, now - prog["lastAdvanceAgeMs"] / 1000.0)
            out.append(StuckCandidate(
                q.id, thr, last, trace_id=q.trace_ctx.trace_id,
                extra={"state": state, "user": q.user,
                       "query": q.text[:200]}))
        return out

    def cluster_doc(self) -> dict:
        """The fleet overview ``GET /v1/cluster`` serves (the reference
        coordinator's ClusterStatsResource analog): live query counts +
        per-query progress, per-worker liveness/occupancy rows probed
        over ``GET /v1/status``, aggregate throughput, resource-group
        queue depths, and the stuck-progress watchdog total. One
        probe refreshes the workers-alive gauge cache."""
        from ..exec.progress import live_snapshots, live_task_count
        from .client import pull_worker_docs
        from .watchdog import stuck_totals
        now = time.time()
        with self._qlock:
            queries = list(self._queries.values())
        queued = running = 0
        running_docs = []
        for q in queries:
            state = q.machine.state
            if state in TERMINAL_STATES:
                continue
            if state == QueryState.QUEUED:
                queued += 1
            else:
                running += 1
            running_docs.append({
                "queryId": q.id, "user": q.user, "state": state,
                "elapsedMs": q.machine.elapsed_ms(),
                "query": q.text[:120],
                "traceId": q.trace_ctx.trace_id,
                "progress": self._progress_doc(q),
                # worst finalized q-error (None until the estimate
                # ledger closed out -- FINISHING queries show it while
                # the client still drains); the ptop per-query column
                "maxQError": _max_q_error_of(q.id)})
        groups = self.dispatcher.group_stats()
        blocked = sum(int(g.get("queued", 0)) for g in groups.values())
        from .discovery import recently_unannounced
        all_urls = self._worker_urls()
        # a worker that UNANNOUNCED (graceful goodbye / completed
        # drain) drops out of the probed set IMMEDIATELY -- probing it
        # until some ttl expired made drained workers flap
        # dead-then-alive in the workers-alive gauge. The goodbye
        # registry is PROCESS-local (worker drains and the discovery
        # DELETE handler feed it); statement tiers running in their
        # own process should wire `profile_workers` to a discovery-
        # backed callable instead -- alive_nodes drops unannounced
        # nodes immediately by construction
        gone = set(recently_unannounced())
        urls = [u for u in all_urls if str(u).rstrip("/") not in gone]
        workers, alive = pull_worker_docs(
            urls, 2.0, lambda c: {**c.status(), "uri": c.base},
            "statement", "cluster_status", parallel=True,
            placeholder=lambda u: {"uri": u, "nodeId": u,
                                   "state": "DEAD",
                                   "fleetState": "DEAD", "memory": {}})
        for w in workers:
            # older workers without the elastic state machine map their
            # legacy flat state onto it
            w.setdefault("fleetState",
                         "DRAINING" if w.get("state") == "SHUTTING_DOWN"
                         else str(w.get("state", "ACTIVE")))
        draining = sum(1 for w in workers
                       if w.get("fleetState") == "DRAINING")
        with self._metrics_lock:
            self._workers_alive = alive
            self._workers_draining = draining
            by_state = dict(self._queries_by_state)
            totals = dict(self._totals)
        live = live_snapshots()
        rows_per_s = sum(e["rows"] / max(e["elapsedMs"] / 1000.0, 1e-3)
                         for e in live)
        return {
            "tsUs": int(now * 1e6),
            "nodeVersion": {"version": "presto-tpu-0.4"},
            "uptimeSeconds": round(now - self._started_at, 1),
            "queries": {"queued": queued, "running": running,
                        "blocked": blocked,
                        "finishedTotal": by_state.get("FINISHED", 0),
                        "failedTotal": by_state.get("FAILED", 0),
                        "canceledTotal": by_state.get("CANCELED", 0)},
            "runningQueries": running_docs,
            "liveTasks": live_task_count(),
            "rowsPerSecond": round(rows_per_s, 1),
            "totals": {"rows": totals["rows"], "bytes": totals["bytes"],
                       "wallSeconds": round(totals["wall_us"] / 1e6, 3)},
            "resourceGroups": groups,
            # live batching view: per-group queue depth rides
            # resourceGroups above; this is the dispatch-amortization
            # side (current occupancy, forming queues, collapses)
            "batching": self._batching_doc(),
            "workers": workers,
            "workersAlive": alive,
            # the CONFIGURED count keeps counting unannounced workers
            # (they are configured, just gone): ptop's alive/configured
            # ratio is where a missing worker shows
            "workersConfigured": len(all_urls),
            "workersDraining": draining,
            "workersDead": sum(1 for w in workers
                               if w.get("fleetState") == "DEAD"),
            "workersUnannounced": len(all_urls) - len(urls),
            "stuckQueriesTotal": stuck_totals(),
            # data-path staging rate + cached bottleneck hop (the ptop
            # header; a cluster frame never pays the ceilings probe)
            "datapath": self._datapath_summary(),
            # estimate-accuracy lifetime summary (worst q-error + its
            # node): the ptop header's accuracy line
            "accuracy": self._accuracy_summary(),
            # execution-timeline occupancy headline (overlap fraction,
            # device-idle wall): the ptop occupancy line
            "timeline": self._timeline_summary(),
        }

    def _accuracy_summary(self) -> dict:
        """The cheap per-frame accuracy embed (never fails the fleet
        overview)."""
        try:
            from ..exec.accuracy import accuracy_summary
            return accuracy_summary()
        except Exception as e:  # noqa: BLE001 - introspection must not
            # take down the fleet overview
            from .metrics import record_suppressed
            record_suppressed("statement", "accuracy_summary", e)
            return {}

    def _datapath_summary(self) -> dict:
        """The cheap per-frame datapath embed (never fails the fleet
        overview)."""
        try:
            from ..exec.datapath import staging_summary
            return staging_summary()
        except Exception as e:  # noqa: BLE001 - introspection must not
            # take down the fleet overview
            from .metrics import record_suppressed
            record_suppressed("statement", "datapath_summary", e)
            return {}

    def _timeline_summary(self) -> dict:
        """The cheap per-frame occupancy embed (never fails the fleet
        overview)."""
        try:
            from ..exec.timeline import timeline_summary
            return timeline_summary()
        except Exception as e:  # noqa: BLE001 - introspection must not
            # take down the fleet overview
            from .metrics import record_suppressed
            record_suppressed("statement", "timeline_summary", e)
            return {}

    def _batching_doc(self) -> dict:
        """The batching executor's live snapshot for /v1/cluster
        (never fails the cluster doc)."""
        try:
            from ..exec.batching import batching_snapshot
            return batching_snapshot()
        except Exception as e:  # noqa: BLE001 - introspection must not
            # take down the fleet overview
            from .metrics import record_suppressed
            record_suppressed("statement", "batching_doc", e)
            return {}

    def _workers_alive_view(self) -> int:
        """The workers-alive gauge value: the last /v1/cluster probe's
        count, or the configured count before any probe (metrics
        scrapes never pay an HTTP probe themselves)."""
        with self._metrics_lock:
            alive = self._workers_alive
        return len(self._worker_urls()) if alive is None else alive

    def metric_families(self):
        """Coordinator-side /v1/metrics families (shared emitter:
        metrics.py; the worker serves its own set through the same
        module so format/naming cannot drift)."""
        from .metrics import MetricFamily as MF
        with self._qlock:
            live = [q.machine.state for q in self._queries.values()]
        queued = sum(1 for s in live if s == QueryState.QUEUED)
        running = sum(1 for s in live
                      if s not in (QueryState.QUEUED, *TERMINAL_STATES))
        with self._metrics_lock:
            by_state = dict(self._queries_by_state)
            totals = dict(self._totals)
        fam_q = MF("presto_tpu_queries_total", "counter",
                   "terminal queries by final state")
        for st in sorted(by_state):
            fam_q.add(by_state[st], {"state": st})
        if not by_state:
            fam_q.add(0, {"state": "FINISHED"})
        fams = [
            fam_q,
            MF("presto_tpu_queries_queued", "gauge",
               "queries currently QUEUED").add(queued),
            MF("presto_tpu_queries_running", "gauge",
               "queries currently executing").add(running),
            MF("presto_tpu_query_rows_total", "counter",
               "result rows returned to clients").add(totals["rows"]),
            MF("presto_tpu_query_bytes_total", "counter",
               "result bytes produced").add(totals["bytes"]),
            MF("presto_tpu_query_wall_seconds_total", "counter",
               "wall time of terminal queries").add(
                   totals["wall_us"] / 1e6),
            MF("presto_tpu_query_compile_seconds_total", "counter",
               "XLA compile time across queries").add(
                   totals["compile_us"] / 1e6),
            MF("presto_tpu_query_execute_seconds_total", "counter",
               "device execute time across queries").add(
                   totals["execute_us"] / 1e6),
            MF("presto_tpu_query_peak_memory_bytes", "gauge",
               "largest per-query peak memory seen").add(
                   totals["peak_memory_bytes"]),
        ]
        from .metrics import (batching_families, datapath_families,
                              donation_families, failpoint_families,
                              fleet_families, flight_recorder_families,
                              histogram_families, kernel_audit_families,
                              live_introspection_families,
                              narrowing_families, plan_cache_families,
                              query_history_families,
                              suppressed_error_families,
                              tracing_families, uptime_family)
        fams.append(uptime_family(self._started_at, "coordinator"))
        fams.extend(live_introspection_families(
            workers_alive=self._workers_alive_view()))
        with self._metrics_lock:
            draining = self._workers_draining
        fams.extend(fleet_families(workers_draining=draining))
        fams.extend(plan_cache_families())
        fams.extend(narrowing_families())
        fams.extend(datapath_families())
        from .metrics import accuracy_families
        fams.extend(accuracy_families())
        fams.extend(batching_families())
        fams.extend(suppressed_error_families())
        fams.extend(tracing_families())
        fams.extend(flight_recorder_families())
        fams.extend(kernel_audit_families())
        fams.extend(donation_families())
        fams.extend(failpoint_families())
        from .metrics import timeline_families
        fams.extend(timeline_families())
        from .metrics import lock_families
        fams.extend(lock_families())
        fams.extend(query_history_families())
        fams.extend(histogram_families())
        return fams

    def profile_doc(self) -> dict:
        """Cluster-merged per-kernel profile for GET /v1/profile: this
        process's slice plus every configured worker's, folded by
        fingerprint (exec/profiler.py; process-id dedup keeps an
        in-process worker from double-counting)."""
        from ..exec.profiler import cluster_profile_doc
        return cluster_profile_doc(self._worker_urls())

    def history_doc(self) -> dict:
        """Cluster-merged completed-query history for GET /v1/history
        (server/history.py): this process's archive slice plus every
        configured worker's, newest-first, deduplicated by processId
        like the profile merge."""
        from .history import cluster_history_doc
        return cluster_history_doc(self._worker_urls())

    def datapath_doc(self) -> dict:
        """Cluster-merged per-hop data-path ledger for GET
        /v1/datapath: this process's slice plus every configured
        worker's, folded by hop (exec/datapath.py; processId dedup
        keeps an in-process worker from double-counting, exactly like
        the profile merge)."""
        from ..exec.datapath import cluster_datapath_doc
        return cluster_datapath_doc(self._worker_urls())

    def accuracy_doc(self) -> dict:
        """Cluster-merged estimate-accuracy ledger for GET
        /v1/accuracy: this process's slice plus every configured
        worker's, per-query records stitched by the NodeAccuracy merge
        law (exec/accuracy.py; processId dedup keeps an in-process
        worker from double-counting, exactly like the profile merge)."""
        from ..exec.accuracy import cluster_accuracy_doc
        return cluster_accuracy_doc(self._worker_urls())

    def timeline_doc(self) -> dict:
        """Cluster-merged execution-timeline ledger for GET
        /v1/timeline: this process's slice plus every configured
        worker's, per-query interval slices stitched on a shared
        reference clock (exec/timeline.py; processId dedup keeps an
        in-process worker from double-counting, exactly like the
        profile merge)."""
        from ..exec.timeline import cluster_timeline_doc
        return cluster_timeline_doc(self._worker_urls())

    def _worker_urls(self) -> list:
        """The worker base URLs the cluster-merged surfaces
        (/v1/profile, /v1/history) pull slices from."""
        pw = self._profile_workers
        return list(pw() if callable(pw) else (pw or ()))


def _render_ui(server: "StatementServer", parts: List[str]) -> str:
    """Minimal coordinator UI (presto-ui's QueryList/QueryDetail pages,
    server-rendered): /ui lists queries, /ui/query/<id> shows one."""
    import html as H

    style = ("<style>body{font-family:monospace;margin:2em}"
             "table{border-collapse:collapse}"
             "td,th{border:1px solid #999;padding:4px 8px;text-align:left}"
             "th{background:#eee}.FINISHED{color:#080}"
             ".FAILED{color:#b00}.RUNNING{color:#06c}</style>")
    if len(parts) == 2 and parts[0] == "query":
        doc = server.admin_doc(parts[1])
        if doc is None:
            return f"{style}<h2>query {H.escape(parts[1])} not found</h2>"
        rows = "".join(
            f"<tr><th>{H.escape(str(k))}</th>"
            f"<td><pre>{H.escape(json.dumps(v, indent=1, default=str))}"
            f"</pre></td></tr>" for k, v in doc.items())
        return (f"{style}<h2>query {H.escape(parts[1])}</h2>"
                f"<p><a href='/ui'>&larr; queries</a></p>"
                f"<table>{rows}</table>")
    docs = sorted(server.queries_doc(),
                  key=lambda d: d.get("timings", {}).get("QUEUED", 0),
                  reverse=True)
    rows = "".join(
        f"<tr><td><a href='/ui/query/{H.escape(d['queryId'])}'>"
        f"{H.escape(d['queryId'])}</a></td>"
        f"<td class='{H.escape(d['state'])}'>{H.escape(d['state'])}</td>"
        f"<td>{H.escape(d['user'])}</td>"
        f"<td>{d.get('elapsedTimeMillis', 0)} ms</td>"
        f"<td>{H.escape(d['query'][:120])}</td></tr>" for d in docs)
    return (f"{style}<h2>presto-tpu coordinator</h2>"
            f"<p>{len(docs)} queries (TTL {server.query_ttl_s:.0f}s)</p>"
            f"<table><tr><th>query</th><th>state</th><th>user</th>"
            f"<th>elapsed</th><th>sql</th></tr>{rows}</table>")


def _parse_session_header(value: str) -> Dict[str, str]:
    out = {}
    for part in value.split(","):
        part = part.strip()
        if part and "=" in part:
            k, v = part.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def _make_handler(server: StatementServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet
            pass

        def _send(self, doc, code=200, headers: Optional[Dict] = None):
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):  # noqa: N802
            parts = [p for p in self.path.split("/") if p]
            if parts == ["v1", "failpoint"]:
                length = int(self.headers.get("Content-Length", "0") or 0)
                body = json.loads(self.rfile.read(length) or b"{}")
                doc, code = failpoints.admin_post(body)
                self._send(doc, code)
                return
            if self.path.rstrip("/") != "/v1/statement":
                self._send({"error": "not found"}, 404)
                return
            length = int(self.headers.get("Content-Length", "0") or 0)
            text = self.rfile.read(length).decode("utf-8", "replace")
            if not text.strip():
                self._send(_error_doc("SYNTAX_ERROR", "empty statement"),
                           400)
                return
            user = self.headers.get("X-Presto-User", "anonymous")
            session_values = _parse_session_header(
                self.headers.get("X-Presto-Session", ""))
            src = self.headers.get("X-Presto-Source")
            if src:
                session_values.setdefault("source", src)
            tags = self.headers.get("X-Presto-Client-Tags")
            if tags:
                session_values.setdefault(
                    "clientTags", [t.strip() for t in tags.split(",")
                                   if t.strip()])
            txn = self.headers.get("X-Presto-Transaction-Id")
            if txn in (None, "", "NONE"):
                txn = None
            from .tracing import TRACE_HEADER, parse_traceparent
            client_ctx = parse_traceparent(
                self.headers.get(TRACE_HEADER))
            q = server.create_query(text, user, session_values, txn,
                                    client_ctx=client_ctx)
            # give fast statements a beat to leave QUEUED (the reference
            # responds immediately; one poll saves a client round trip)
            q.machine.wait_past_queued(0.05)
            self._send(server.queued_doc(q, 0))

        def do_GET(self):  # noqa: N802
            parts = [p for p in self.path.split("/") if p]
            # /v1/statement/{queued|executing}/{id}/{slug}/{token}
            if len(parts) == 6 and parts[:2] == ["v1", "statement"] and \
                    parts[2] in ("queued", "executing"):
                q = server.get_query(parts[3], parts[4])
                if q is None:
                    self._send({"error": "query not found"}, 404)
                    return
                token = int(parts[5])
                headers = {}
                if parts[2] == "queued":
                    q.machine.wait_past_queued(server.queue_poll_s)
                    doc = server.queued_doc(q, token)
                else:
                    q.machine.wait_done(server.queue_poll_s)
                    doc = server.executing_doc(q, token)
                    if q.machine.is_done():
                        for k, v in q.set_session.items():
                            headers["X-Presto-Set-Session"] = f"{k}={v}"
                        if q.started_txn:
                            headers["X-Presto-Started-Transaction-Id"] = \
                                q.started_txn
                        if q.clear_txn:
                            headers["X-Presto-Clear-Transaction-Id"] = "true"
                self._send(doc, headers=headers)
                return
            if parts == ["v1", "cluster"]:
                # fleet overview: live query/task progress + per-worker
                # liveness/occupancy (ClusterStatsResource analog; the
                # document scripts/ptop.py renders)
                self._send(server.cluster_doc())
                return
            if parts == ["v1", "profile"]:
                # cluster-merged per-kernel device-time table (the
                # continuous profiler's coordinator surface)
                self._send(server.profile_doc())
                return
            if parts == ["v1", "datapath"]:
                # cluster-merged per-hop byte/throughput ledger with
                # roofline bottleneck verdicts (exec/datapath.py)
                self._send(server.datapath_doc())
                return
            if parts == ["v1", "accuracy"]:
                # cluster-merged per-plan-node estimate-vs-actual
                # ledger with misestimate verdicts (exec/accuracy.py)
                self._send(server.accuracy_doc())
                return
            if parts == ["v1", "timeline"]:
                # cluster-merged execution-timeline ledger with
                # occupancy/bubble verdicts (exec/timeline.py)
                self._send(server.timeline_doc())
                return
            if parts == ["v1", "history"]:
                # cluster-merged completed-query archive (the perf
                # sentinel's raw material; server/history.py)
                self._send(server.history_doc())
                return
            if parts == ["v1", "failpoint"]:
                # fault-injection admin surface (mirrors the worker's)
                self._send(failpoints.admin_get_doc())
                return
            if len(parts) == 3 and parts[:2] == ["v1", "trace"]:
                doc = server.trace_doc(parts[2])
                self._send(doc if doc else
                           {"error": f"no trace for {parts[2]} (is a "
                                     f"tracer installed?)"},
                           200 if doc else 404)
                return
            if len(parts) == 3 and parts[:2] == ["v1", "query"]:
                doc = server.admin_doc(parts[2])
                self._send(doc if doc else {"error": "not found"},
                           200 if doc else 404)
                return
            if parts == ["v1", "query"]:
                self._send(server.queries_doc())
                return
            if parts == ["v1", "info"]:
                self._send({"nodeVersion": {"version": "presto-tpu-0.4"},
                            "coordinator": True, "starting": False,
                            "uptime": "0m"})
                return
            if parts == ["v1", "metrics"]:
                from .metrics import (negotiate_exposition,
                                      render_prometheus)
                om, ctype = negotiate_exposition(
                    self.headers.get("Accept"))
                body = render_prometheus(server.metric_families(),
                                         openmetrics=om)
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if parts[:1] == ["ui"]:
                self._send_html(_render_ui(server, parts[1:]))
                return
            self._send({"error": "not found"}, 404)

        def _send_html(self, html: str, code: int = 200):
            body = html.encode()
            self.send_response(code)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_DELETE(self):  # noqa: N802
            parts = [p for p in self.path.split("/") if p]
            if parts[:2] == ["v1", "failpoint"] and len(parts) in (2, 3):
                self._send(failpoints.admin_delete(
                    parts[2] if len(parts) == 3 else None))
                return
            if len(parts) >= 5 and parts[:2] == ["v1", "statement"]:
                q = server.get_query(parts[3], parts[4])
                if q is None:
                    self._send({"error": "query not found"}, 404)
                    return
                server.cancel(q)
                self._send({"id": q.id, "canceled": True}, 200)
                return
            self._send({"error": "not found"}, 404)

    return Handler
