"""Event listeners: query lifecycle events fanned out to plugins.

Reference surface: presto-spi/.../spi/eventlistener/ (QueryCreatedEvent,
QueryCompletedEvent, SplitCompletedEvent) dispatched by
EventListenerManager to every registered plugin listener (the
openlineage emitter is one consumer).

Here events are plain dicts (the JSON the reference serializes anyway)
and listeners are callables registered on the process-global manager;
the engine fires QueryCreated/QueryCompleted around run_query and
TaskCompleted on the worker. Listener errors are swallowed (the
reference logs-and-continues: observers must not fail queries).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List

from ..utils.locks import OrderedLock

__all__ = ["EventListenerManager", "event_listeners"]


class EventListenerManager:
    def __init__(self):
        self._listeners: List[Callable[[str, Dict], None]] = []
        self._lock = OrderedLock("events.EventListenerManager._lock")

    def register(self, listener: Callable[[str, Dict], None]):
        """listener(event_name, payload). Returns an unregister handle."""
        with self._lock:
            self._listeners.append(listener)

        def unregister():
            with self._lock:
                try:
                    self._listeners.remove(listener)
                except ValueError:
                    pass
        return unregister

    def fire(self, name: str, payload: Dict):
        payload = dict(payload)
        payload.setdefault("timestampMs", int(time.time() * 1000))
        with self._lock:
            listeners = list(self._listeners)
        for cb in listeners:
            try:
                cb(name, payload)
            except Exception as e:  # noqa: BLE001 - observers never
                # fail queries; a broken listener is still worth a count
                from .metrics import record_suppressed
                record_suppressed("events", "listener", e)

    def query_created(self, query_id: str, text: str = "", user: str = ""):
        self.fire("QueryCreated", {"queryId": query_id, "query": text,
                                   "user": user})

    def query_completed(self, query_id: str, state: str, rows: int = 0,
                        wall_s: float = 0.0, error: str = ""):
        self.fire("QueryCompleted", {"queryId": query_id, "state": state,
                                     "outputRows": rows,
                                     "wallTimeSeconds": wall_s,
                                     "error": error})

    def task_completed(self, task_id: str, state: str, rows: int = 0):
        self.fire("TaskCompleted", {"taskId": task_id, "state": state,
                                    "outputRows": rows})


_MANAGER = EventListenerManager()


def event_listeners() -> EventListenerManager:
    """The process-global manager (EventListenerManager analog)."""
    return _MANAGER
