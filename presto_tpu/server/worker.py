"""Worker control/data plane: the TaskResource / TaskManager analog.

Reference surface: the worker REST API contract
(presto-docs/.../develop/worker-protocol.rst; Java TaskResource.java:79
createOrUpdate:118 status-long-poll:182 results:283 acknowledge:244;
C++ presto_cpp/main/TaskResource.cpp + TaskManager.cpp:506) and the
discovery announcer (presto_cpp/main/Announcer.cpp).

Endpoints (coordinator-facing contract):
  GET    /v1/info                     server info (node id, state, uptime)
  GET    /v1/status                   node status (memory, tasks)
  POST   /v1/task/{taskId}            create/update: body carries the plan
                                      JSON + scan config (TaskUpdateRequest
                                      analog); idempotent
  GET    /v1/task/{taskId}            TaskInfo JSON (state, stats)
  GET    /v1/task/{taskId}/results/{bufferId}/{token}
                                      SerializedPage bytes; token/ack pull
                                      protocol with X-Presto-Page-* headers
  GET    /v1/task/{taskId}/results/{bufferId}/{token}/acknowledge
  DELETE /v1/task/{taskId}            abort

Execution runs on a background thread per task (the TPU device stream
serializes actual kernels); results buffer as SerializedPages with
monotonically increasing tokens, deleted on ack -- the same
at-least-once pull contract the reference's ExchangeClient speaks.

This is the Python control-plane shell; the reference keeps its shell in
C++ for RPC-throughput reasons and a C++ port of this module is planned
once the protocol stabilizes (SURVEY.md §2.3).
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

import numpy as np

from .. import failpoints
from ..plan import nodes as N
from ..serde import PageCodec, serialize_page
from ..utils.config import Session
from ..utils.locks import OrderedLock
from .buffers import SpoolingOutputBuffer

__all__ = ["TpuWorkerServer", "TaskManager"]


def _hash_partition_rows(res, channels: List[int], nparts: int):
    """Destination partition per result row, using the engine's row hash
    (expr.functions splitmix64) so routing matches on-device exchanges.
    Returns a list of index arrays, one per partition."""
    import numpy as np

    from .. import types as _T
    from ..block import batch_from_numpy
    from ..expr.functions import combine_hash, hash64_block

    n = res.row_count
    if n == 0:
        return [np.array([], dtype=np.int64)] * nparts
    tys = [res.types[c] if res.types else _T.BIGINT for c in channels]
    key_batch = batch_from_numpy(tys, [res.columns[c] for c in channels],
                                 [res.nulls[c] for c in channels])
    h = None
    for i in range(len(channels)):
        hc = hash64_block(key_batch.column(i))
        h = hc if h is None else combine_hash(h, hc)
    dest = np.asarray(h % np.uint64(nparts)).astype(np.int64)
    return [np.nonzero(dest == p)[0] for p in range(nparts)]


class _GoneError(Exception):
    """Requested pages were acked away by a prior consumer (HTTP 410)."""


class _MovedError(Exception):
    """The task's buffered pages migrated to a peer during graceful
    drain; str(self) is the adopting worker's base url. The HTTP layer
    answers with an ``X-Presto-Task-Moved`` header and the consumer
    (WorkerClient.fetch_results) resumes its token stream against the
    peer -- tokens are absolute and the acked prefix migrated with the
    pages, so the replay is exactly-once by construction."""


class FragmentResultCache:
    """Leaf-fragment output cache (FileFragmentResultCacheManager
    analog): serialized result pages keyed by (canonical plan
    fingerprint, sf, scan ranges, output partitioning, connector data
    versions). Deterministic generator scans key on sf alone; memory
    tables key on their mutation counters; parquet on file mtimes;
    volatile catalogs (system) are uncacheable. Bounded LRU by bytes."""

    # write-barrier contract, enforced statically (tpulint C001)
    _GUARDED_BY = {"_lock": ("_entries", "_bytes", "hits", "misses")}

    def __init__(self, max_bytes: int = 256 << 20):
        import collections
        self.max_bytes = max_bytes
        self._entries = collections.OrderedDict()
        self._bytes = 0
        self._lock = OrderedLock("worker.FragmentResultCache._lock")
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key_of(plan: N.PlanNode, sf: float, scan_ranges: dict,
               out_part, compression) -> Optional[tuple]:
        """None = not cacheable."""
        scans: List[N.TableScanNode] = []

        def walk(n):
            if isinstance(n, (N.RemoteSourceNode, N.TableWriterNode,
                              N.TableFinishNode, N.TableRewriteNode,
                              N.DdlNode)):
                # remote inputs aren't pure; writes/DDL are SIDE EFFECTS
                # a replayed page must never skip
                scans.append(None)
            if isinstance(n, N.TableScanNode):
                scans.append(n)
            for s in n.sources:
                walk(s)
        walk(plan)
        versions = []
        for s in scans:
            if s is None:
                return None
            # connector-level seam: a catalog is cacheable iff it
            # exposes data_version(table) (system & unknown catalogs
            # don't -- volatile by default)
            from ..connectors import catalog as _catalog
            try:
                fn = getattr(_catalog(s.connector), "data_version", None)
                if fn is None:
                    return None
                versions.append((s.connector, s.table, fn(s.table)))
            except KeyError:
                return None  # table/catalog vanished: don't cache
        from ..exec.plan_cache import plan_fingerprint
        return (plan_fingerprint(plan), sf,
                tuple(sorted((k, tuple(v)) for k, v in scan_ranges.items())),
                repr(out_part), compression, tuple(versions))

    def get(self, key) -> Optional[dict]:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return e

    def put(self, key, buffers: Dict[int, List[bytes]], rows: int,
            stats: Dict[str, float]) -> None:
        size = sum(len(p) for pages in buffers.values() for p in pages)
        if size > self.max_bytes:
            return
        with self._lock:
            if key in self._entries:
                return
            self._entries[key] = {"buffers": {k: list(v) for k, v
                                              in buffers.items()},
                                  "rows": rows, "stats": dict(stats),
                                  "bytes": size}
            self._bytes += size
            while self._bytes > self.max_bytes and self._entries:
                _k, old = self._entries.popitem(last=False)
                self._bytes -= old["bytes"]


class _Task:
    # every field the HTTP threads and the execution thread share is
    # written under the task lock (tpulint C001 enforces this, module-
    # wide: TaskManager's writes through `task.` are checked too)
    _GUARDED_BY = {"lock": ("state", "error", "buffers", "first_token",
                            "no_more_pages", "stats", "finished_at",
                            "spans", "moved_to")}

    def __init__(self, task_id: str, spool_threshold: int = 64 << 20,
                 spool_dir: Optional[str] = None,
                 session_stuck_ms=None):
        self.task_id = task_id
        self.state = "PLANNED"  # PLANNED -> RUNNING -> FINISHED/FAILED/ABORTED
        self.error: Optional[str] = None
        self._spool_threshold = spool_threshold
        self._spool_dir = spool_dir
        # the task body session's stuck_query_threshold_ms (None =
        # resolve the PRESTO_TPU_STUCK_MS env at watchdog scan time)
        self.session_stuck_ms = session_stuck_ms
        # partition-addressed output buffers (OutputBufferId -> pages);
        # unpartitioned results live in buffer 0. Pages past the memory
        # budget spool to disk (SpoolingOutputBuffer.java analog)
        self.buffers: Dict[int, SpoolingOutputBuffer] = {
            0: self._new_buffer()}
        self.first_token: Dict[int, int] = {}  # per-buffer acked prefix
        self.no_more_pages = False
        # base url of the peer this task's pages migrated to during a
        # graceful drain (None = pages are local); once set, result
        # pulls redirect and local acks are no-ops
        self.moved_to: Optional[str] = None
        self.created_at = time.time()
        self.finished_at: Optional[float] = None
        self.stats: Dict[str, float] = {}
        # the task's local span docs, set once at terminal state: they
        # ship to the coordinator piggybacked on the final task status
        # (the distributed-trace stitch transport)
        self.spans: List[dict] = []
        self.lock = OrderedLock("worker._Task.lock")

    def _new_buffer(self) -> SpoolingOutputBuffer:
        return SpoolingOutputBuffer(self._spool_threshold, self._spool_dir)

    def info(self) -> dict:
        # live progress rides every TaskInfo poll: the coordinator's
        # status loop folds it back into its own registry, so the
        # statement tier sees cross-worker heartbeats without a second
        # protocol (registry lock nests inside the task lock and never
        # takes it back -- no cycle)
        from ..exec.progress import get_progress
        ent = get_progress(self.task_id)
        with self.lock:
            doc = {
                "taskId": self.task_id,
                "state": self.state,
                "error": self.error,
                "bufferedPages": sum(len(p) for p in self.buffers.values()),
                "spooledBytes": sum(b.spooled_bytes
                                    for b in self.buffers.values()),
                "noMorePages": self.no_more_pages,
                "stats": dict(self.stats),
                "elapsedSeconds": round(time.time() - self.created_at, 3),
            }
            if self.moved_to is not None:
                doc["movedTo"] = self.moved_to
            if ent is not None:
                doc["progress"] = ent.snapshot()
            if self.spans:
                # populated only at terminal state, so in-flight status
                # polls stay small and the final poll carries the spans
                doc["spans"] = list(self.spans)
            return doc


class TaskManager:
    """createOrUpdateTask / result-buffer bookkeeping (TaskManager.cpp:506
    analog). Execution admits through a bounded slot pool
    (`task_concurrency` concurrent plans, the TaskExecutor analog of
    execution/executor/TaskExecutor.java:87): a long task occupies one
    slot while short tasks proceed through the others, and HBM admission
    stays with the shared MemoryPool each run_query reserves from. XLA
    serializes actual device streams; overlapping tasks still overlap
    their host-side staging, serde, and compile phases, which dominate
    short-task latency."""

    # `draining`/`drained` ride the tasks lock: create_or_update reads
    # them under _tasks_lock to make the refuse-new-tasks decision
    # atomic with task creation (write paths: drain(), mark_drained())
    _GUARDED_BY = {"_tasks_lock": ("tasks", "draining", "drained"),
                   "_counters_lock": ("counters",)}

    def __init__(self, sf: float = 0.01, mesh=None,
                 memory_bytes: int = 12 << 30,
                 task_ttl_s: float = 600.0,
                 task_concurrency: int = 4,
                 output_spool_threshold_bytes: int = 64 << 20,
                 output_spool_dir: Optional[str] = None):
        from ..exec.memory import MemoryPool
        self.sf = sf
        self.mesh = mesh
        self.tasks: Dict[str, _Task] = {}
        # concurrent tasks contend for HBM admission: waits (bounded)
        # beat failing a query that fit fine under serial execution
        self.memory_pool = MemoryPool(memory_bytes,
                                      admission_timeout_s=60.0)
        self.draining = False  # GracefulShutdownHandler state
        self.drained = False   # drain complete: pages replayed/migrated
        self.task_ttl_s = task_ttl_s
        self.task_concurrency = max(1, int(task_concurrency))
        self.output_spool_threshold_bytes = output_spool_threshold_bytes
        self.output_spool_dir = output_spool_dir
        self._exec_slots = threading.BoundedSemaphore(self.task_concurrency)
        self._tasks_lock = OrderedLock("worker.TaskManager._tasks_lock")
        self.fragment_cache = FragmentResultCache()
        from ..connectors.system import register_task_manager
        register_task_manager(self)  # system.tasks introspection
        # lifetime counters for /v1/metrics (Prometheus)
        self.counters: Dict[str, int] = {"tasks_created": 0,
                                         "tasks_finished": 0,
                                         "tasks_failed": 0,
                                         "tasks_aborted": 0,
                                         "tasks_adopted": 0,
                                         "pages_migrated": 0,
                                         "rows_produced": 0,
                                         "exchange_bytes": 0,
                                         "compile_us": 0,
                                         "execute_us": 0}
        self._counters_lock = OrderedLock("worker.TaskManager._counters_lock")

    def _count(self, name: str, delta: int = 1):
        with self._counters_lock:
            self.counters[name] = self.counters.get(name, 0) + delta

    def drain(self) -> None:
        """Enter SHUTTING_DOWN (GracefulShutdownHandler): stop accepting
        NEW tasks, let running ones finish. Under the tasks lock so the
        flag flip is atomic with in-flight create_or_update decisions."""
        with self._tasks_lock:
            self.draining = True

    def mark_drained(self) -> None:
        """Drain complete: every buffered page was replayed or migrated
        (TpuWorkerServer._drain's terminal step)."""
        with self._tasks_lock:
            self.draining = True
            self.drained = True

    @property
    def drain_state(self) -> str:
        """ACTIVE | DRAINING | DRAINED -- the fleet state /v1/status,
        /v1/cluster and ptop render (the legacy flat `state` key keeps
        its SHUTTING_DOWN spelling for older pollers)."""
        with self._tasks_lock:
            if self.drained:
                return "DRAINED"
            return "DRAINING" if self.draining else "ACTIVE"

    def unreplayed_pages(self) -> int:
        """Buffered result pages still owned by THIS worker (migrated
        tasks excluded): the quantity graceful drain must bring to zero
        before the node unannounces."""
        with self._tasks_lock:
            tasks = list(self.tasks.values())
        total = 0
        for t in tasks:
            with t.lock:
                if t.moved_to is None:
                    total += sum(len(b) for b in t.buffers.values())
        return total

    def migrate_buffers(self, peer_url: str, timeout: float = 30.0,
                        secret: Optional[str] = None) -> int:
        """Migrate every finished task's remaining buffered pages to
        `peer_url` (SpoolingOutputBuffer tail included); returns pages
        moved. The export + moved_to flip happen under the task lock in
        ONE critical section, so no consumer can ack a local page after
        its copy shipped (the duplicate-replay hazard); a failed POST
        rolls the flip back and the pages stay served locally -- drain
        degrades to waiting, never loses or doubles a page."""
        from .client import WorkerClient
        from .flight_recorder import record_event
        from .metrics import record_suppressed
        with self._tasks_lock:
            tasks = list(self.tasks.values())
        # the migration hop is an internal hop like any other: it must
        # carry the cluster secret or secured peers 401 every adopt
        client = WorkerClient(peer_url, timeout=timeout,
                              shared_secret=secret)
        moved = 0
        for task in tasks:
            with task.lock:
                if task.moved_to is not None or task.state != "FINISHED" \
                        or not task.no_more_pages:
                    continue
                npages = sum(len(b) for b in task.buffers.values())
                if npages == 0:
                    continue
                doc = {
                    "state": task.state,
                    "noMorePages": True,
                    "stats": dict(task.stats),
                    "firstToken": {str(b): task.first_token.get(b, 0)
                                   for b in task.buffers},
                    "buffers": {str(b): buf.export_pages()
                                for b, buf in task.buffers.items()},
                }
                # optimistic flip: consumers redirect from here on (the
                # peer's adopt races them by at most one short retry)
                task.moved_to = peer_url.rstrip("/")
            try:
                client.migrate(task.task_id, doc)
            except Exception as e:  # noqa: BLE001 - peer refused/died
                record_suppressed("worker", "migrate_task", e)
                # a timed-out POST may still have LANDED: rolling back
                # while the peer serves the adopted copy would let two
                # nodes serve the same pages. Probe before deciding --
                # only a confirmed-absent adopt rolls the flip back
                # (keep serving locally); a confirmed/ambiguous adopt
                # stays moved (consumers redirect, worst case they wait
                # out the adopt exactly like the in-flight window).
                adopted = False
                try:
                    adopted = client.task_info(task.task_id) is not None
                except Exception as pe:  # noqa: BLE001 - 404 or dead
                    # peer: no adopted copy is reachable -> roll back
                    record_suppressed("worker", "migrate_probe", pe)
                if not adopted:
                    with task.lock:
                        task.moved_to = None
                    continue
            with task.lock:
                for b in task.buffers.values():
                    b.clear()
                task.buffers = {}
            moved += npages
            self._count("pages_migrated", npages)
            record_event("buffer_migrate", query_id=task.task_id,
                         pages=npages, to=peer_url)
        return moved

    def adopt_task(self, task_id: str, doc: dict) -> dict:
        """Adopt a draining peer's finished task: restore its buffered
        pages (at their original absolute token offsets) so redirected
        consumers resume their pull streams here. Idempotent; refused
        while this worker is itself draining (like new tasks)."""
        from .flight_recorder import record_event
        with self._tasks_lock:
            self._prune_locked()
            task = self.tasks.get(task_id)
            if task is None:
                if self.draining:
                    raise RuntimeError(
                        "worker is SHUTTING_DOWN: not adopting tasks")
                task = _Task(task_id, self.output_spool_threshold_bytes,
                             self.output_spool_dir)
                self.tasks[task_id] = task
                adopted = True
            else:
                adopted = False
        if not adopted:
            return task.info()
        # restore (and possibly re-spool to disk) OUTSIDE the task
        # lock: only this thread adopts (the `adopted` flag is flipped
        # under _tasks_lock), and a consumer that races the attach sees
        # the same fresh-empty state it could already see between task
        # creation and the old in-lock restore -- its 404-retry covers
        # the window. Holding task.lock across file I/O stalled every
        # /v1/task status poll behind a slow disk (tpulint C003).
        total = 0
        buffers: Dict[int, SpoolingOutputBuffer] = {}
        for bid, pages in (doc.get("buffers") or {}).items():
            buf = task._new_buffer()
            total += buf.restore_pages(pages)
            buffers[int(bid)] = buf
        with task.lock:
            task.buffers = buffers or {0: task._new_buffer()}
            task.first_token = {int(b): int(t) for b, t in
                                (doc.get("firstToken") or {}).items()}
            task.no_more_pages = bool(doc.get("noMorePages", True))
            task.stats = dict(doc.get("stats") or {})
            task.state = str(doc.get("state", "FINISHED"))
            task.finished_at = time.time()
        # already accounted (finished) by the origin worker: only the
        # adoption itself counts
        task._accounted = True
        self._count("tasks_adopted")
        record_event("task_adopt", query_id=task_id, bytes=total)
        return task.info()

    def _prune_locked(self):
        """Drop terminal tasks (and their buffered pages) older than the
        TTL -- coordinators DELETE tasks after consumption, this is the
        backstop against leaked ones growing worker memory forever. Runs
        opportunistically on task lookups AND submissions so an idle-but-
        polled worker also reclaims."""
        cutoff = time.time() - self.task_ttl_s
        for tid in [tid for tid, t in self.tasks.items()
                    if t.finished_at is not None and t.finished_at < cutoff]:
            del self.tasks[tid]

    def create_or_update(self, task_id: str, body: dict) -> dict:
        with self._tasks_lock:
            self._prune_locked()
            task = self.tasks.get(task_id)
            if task is None:
                # drain refuses only NEW tasks; idempotent re-POSTs of
                # running tasks still succeed (create-or-UPDATE contract)
                if self.draining:
                    raise RuntimeError(
                        "worker is SHUTTING_DOWN: not accepting tasks")
                sess = body.get("session") \
                    if isinstance(body.get("session"), dict) else {}
                task = _Task(task_id, self.output_spool_threshold_bytes,
                             self.output_spool_dir,
                             session_stuck_ms=(sess or {}).get(
                                 "stuck_query_threshold_ms"))
                self.tasks[task_id] = task
                self._count("tasks_created")
                threading.Thread(target=self._run, args=(task, body),
                                 daemon=True).start()
        return task.info()

    def active_task_count(self) -> int:
        with self._tasks_lock:
            self._prune_locked()
            return sum(1 for t in self.tasks.values()
                       if t.state in ("PLANNED", "RUNNING"))

    def _stuck_candidates(self):
        """RUNNING tasks offered to the stuck-progress watchdog
        (server/watchdog.py): threshold from the task body's session
        (env fallback resolved at scan time, so a live env flip takes
        effect for already-running tasks), last advance from the live
        progress entry (falling back to task creation -- a task wedged
        before the runner registered anything is exactly the case the
        detector exists for)."""
        from ..exec.progress import get_progress
        from .watchdog import StuckCandidate, resolve_stuck_threshold_ms
        with self._tasks_lock:
            tasks = list(self.tasks.values())
        out = []
        for t in tasks:
            with t.lock:
                state = t.state
            if state != "RUNNING":
                continue
            sess = None if t.session_stuck_ms is None else \
                {"stuck_query_threshold_ms": t.session_stuck_ms}
            thr = resolve_stuck_threshold_ms(sess)
            if thr <= 0:
                continue
            ent = get_progress(t.task_id)
            snap = ent.snapshot() if ent is not None else None
            out.append(StuckCandidate(
                t.task_id, thr,
                snap["lastAdvanceTsUs"] / 1e6 if snap else t.created_at,
                trace_id=snap["query"] if snap else None,
                extra={"stage": snap["stage"] if snap else "start"}))
        return out

    def _run(self, task: _Task, body: dict):
        try:
            # per-task failpoint schedule (the `failpoints` session
            # property): armed for this task's whole scope -- remote
            # fetch, serde, execution -- and restored afterwards
            spec = (body.get("session") or {}).get("failpoints") \
                if isinstance(body.get("session"), dict) else None
            with failpoints.session_scope(spec):
                self._run_inner(task, body)
        finally:
            # every exit path accounts the task exactly once; the
            # mid-execution ABORT early-returns land here uncounted
            if not getattr(task, "_accounted", False):
                task._accounted = True
                with task.lock:
                    state = task.state
                if state == "ABORTED":
                    self._count("tasks_aborted")
                    from .events import event_listeners
                    event_listeners().task_completed(task.task_id,
                                                     "ABORTED")

    def _run_inner(self, task: _Task, body: dict):
        """Trace plumbing around one task execution: parse the
        propagated context (body ``traceparent``, with the legacy
        ``traceId`` as fallback trace grouping), run the task with a
        thread-local SpanBuffer + ambient context installed (so stage
        spans AND outbound exchange fetches carry the trace), then emit
        the task span and pin every locally recorded span onto the task
        for the final-status piggyback the coordinator stitches."""
        from .flight_recorder import get_flight_recorder
        from .tracing import (TraceContext, emit_span, new_span_id,
                              parse_traceparent, span_buffer,
                              trace_context)
        ctx = parse_traceparent(body.get("traceparent"))
        trace_id = (ctx.trace_id if ctx else None) or \
            body.get("traceId") or task.task_id
        task_ctx = TraceContext(trace_id, new_span_id())
        t_start = time.time()
        with span_buffer() as buf, trace_context(task_ctx):
            try:
                self._run_task(task, body, task_ctx)
            finally:
                # the task state machine (not the runner) owns task
                # finality: force the progress entry terminal so a
                # crashed/aborted task never lingers "RUNNING" on the
                # live surfaces
                from ..exec.progress import finish_task
                with task.lock:
                    state = task.state
                    tstats = dict(task.stats)
                finish_task(task.task_id, state)
                emit_span(trace_id, f"task.{task.task_id}",
                          t_start, time.time(),
                          {"state": state,
                           "rows": tstats.get("outputRows", 0),
                           "bytes": tstats.get("outputBytes", 0)},
                          span_id=task_ctx.span_id,
                          parent_id=ctx.span_id if ctx else None)
                # task-lifetime distribution (/v1/metrics histogram),
                # exemplar'd with the propagated trace id
                from .metrics import observe_histogram
                observe_histogram("presto_tpu_task_seconds",
                                  time.time() - t_start,
                                  trace_id=trace_id)
        with task.lock:
            task.spans = buf.spans
        if state == "FAILED":
            # task-tier flight dump: the worker's view of a failed task
            # (the coordinator separately dumps per query)
            get_flight_recorder().maybe_dump(task.task_id, "failed")

    def _run_task(self, task: _Task, body: dict, task_ctx):
        from .flight_recorder import record_event
        try:
            with task.lock:
                task.state = "RUNNING"
            record_event("task_state", query_id=task.task_id,
                         state="RUNNING")
            # progress heartbeat entry registered BEFORE any failpoint/
            # staging work: a task wedged right here (the `hang` site
            # below) is still visible -- with a stalling last-advance
            # age -- to status polls and the stuck-progress watchdog
            from ..exec.progress import begin as progress_begin
            progress_begin(task.task_id, kind="task",
                           query=task_ctx.trace_id)
            if failpoints.ARMED:
                # error = crash mid-task (-> FAILED -> coordinator
                # resubmit); hang/delay = wedged or slow worker
                failpoints.hit("worker.run_task")
            plan = N.from_json(body["plan"])
            session = Session(body.get("session", {}))
            if not session.get("tpu_execution_enabled"):
                raise RuntimeError(
                    "tpu_execution_enabled=false: fragment refused by the "
                    "TPU worker (route to a row-engine cluster)")
            sf = float(body.get("sf", self.sf))
            codec = PageCodec(
                compression=(session.get("exchange_compression")
                             if session.get("exchange_compression") != "none"
                             else None))
            scan_ranges = {k: tuple(v) for k, v in
                           body.get("scanRanges", {}).items()}
            remote_sources = {}
            pad = (self.mesh.devices.size if self.mesh is not None else 1) * 8
            exchange_unpack_s = 0.0
            exchange_in_rows = 0
            for node_id, spec in body.get("remoteSources", {}).items():
                # pull upstream pages peer-to-peer (PrestoExchangeSource);
                # the pull + page decode is the host-visible exchange
                # *unpack* boundary -- timed into the task's QueryStats
                from ..types import parse_type
                from .http_exchange import fetch_remote_batch
                from .tracing import emit_span
                t_ex0 = time.time()
                remote_sources[node_id] = fetch_remote_batch(
                    spec["sources"], spec["taskIds"],
                    [parse_type(t) for t in spec["types"]],
                    pad_multiple=pad,
                    buffer_id=int(spec.get("bufferId", 0)),
                    ack=bool(spec.get("ack", True)),
                    merge_keys=spec.get("mergeKeys"),
                    timeout=float(spec.get("timeoutS", 60.0)))
                exchange_unpack_s += time.time() - t_ex0
                rows_in = int(
                    np.asarray(remote_sources[node_id].active).sum())
                exchange_in_rows += rows_in
                # the pull+decode is a real hop on the query's critical
                # path: one child span per remote source under the task
                emit_span(task_ctx.trace_id, "exchange.fetch",
                          t_ex0, time.time(),
                          {"node": node_id, "rows": rows_in,
                           "upstreams": len(spec.get("taskIds", []))},
                          parent_id=task_ctx.span_id)
            from ..exec.runner import run_query
            # fragment result cache: identical leaf fragments (same
            # canonical plan, splits, data versions) replay their
            # serialized pages without touching the chip
            from ..utils.config import session_flag
            cache_on = session_flag(session, "fragment_result_cache", True)
            ckey = None
            if cache_on and not body.get("remoteSources"):
                ckey = FragmentResultCache.key_of(
                    plan, sf, scan_ranges, body.get("outputPartitions"),
                    session.get("exchange_compression"))
            if ckey is not None:
                hit = self.fragment_cache.get(ckey)
                record_event("fragment_cache",
                             query_id=task.task_id,
                             hit=hit is not None)
                if hit is not None:
                    # a replay produced rows without touching the chip:
                    # re-shipping the ORIGINAL run's compile/execute
                    # micros would attribute device time to a query
                    # that did none -- keep rows/bytes, mark the replay
                    replay_stats = {k: v for k, v in hit["stats"].items()
                                    if k != "queryStats"}
                    orig_qs = hit["stats"].get("queryStats") or {}
                    replay_stats["queryStats"] = {
                        "wallUs": 0,
                        "outputRows": int(orig_qs.get("outputRows", 0)),
                        "outputBytes": int(orig_qs.get("outputBytes", 0)),
                        "taskCount": 1,
                        "counters": {"fragment_cache_replay": 1}}
                    with task.lock:
                        if task.state == "ABORTED":
                            return
                        for pid, pages in hit["buffers"].items():
                            task.buffers.setdefault(
                                pid, task._new_buffer()).extend(pages)
                        task.no_more_pages = True
                        task.stats = {**replay_stats,
                                      "fragmentCacheHit": 1}
                        task.state = "FINISHED"
                        task.finished_at = time.time()
                    task._accounted = True
                    self._count("tasks_finished")
                    self._count("rows_produced", hit["rows"])
                    record_event("task_state", query_id=task.task_id,
                                 state="FINISHED", cache_replay=True)
                    from .events import event_listeners
                    event_listeners().task_completed(task.task_id,
                                                     "FINISHED",
                                                     hit["rows"])
                    return
            t0 = time.time()
            with self._exec_slots:
                # trace context: the coordinator propagates one trace
                # per query; stage spans parent under THIS task's span
                res = run_query(plan, sf=sf, mesh=self.mesh,
                                scan_ranges=scan_ranges,
                                remote_sources=remote_sources,
                                memory_pool=self.memory_pool,
                                query_id=task.task_id,
                                session=session,
                                trace_id=task_ctx)
            wall = time.time() - t0
            with task.lock:
                if task.state == "ABORTED":
                    return  # abandoned by the coordinator: drop results
            types = plan.output_types()
            out_part = body.get("outputPartitions")
            total_bytes = 0
            built: Dict[int, List[bytes]] = {}
            t_pack0 = time.time()
            if out_part:
                # PartitionedOutputBuffer analog: rows hash to one page
                # per destination partition (same hash as the engine's
                # exchanges -> consistent routing across tiers).
                # Serialize OUTSIDE the lock: status polls keep flowing.
                nparts = int(out_part["count"])
                channels = list(out_part["channels"])
                parts = _hash_partition_rows(res, channels, nparts)
                pages = []
                for pid in range(nparts):
                    sel = parts[pid]
                    cols = [(types[i], res.columns[i][sel],
                             res.nulls[i][sel])
                            for i in range(len(res.columns))]
                    page = serialize_page(cols, codec)
                    total_bytes += len(page)
                    pages.append(page)
                with task.lock:
                    if task.state == "ABORTED":
                        return
                    for pid, page in enumerate(pages):
                        task.buffers.setdefault(
                            pid, task._new_buffer()).append(page)
                built = {pid: [page] for pid, page in enumerate(pages)}
            else:
                cols = [(types[i], res.columns[i], res.nulls[i])
                        for i in range(len(res.columns))]
                page = serialize_page(cols, codec)
                total_bytes = len(page)
                with task.lock:
                    if task.state == "ABORTED":
                        return
                    task.buffers[0].append(page)
                built = {0: [page]}
            pack_s = time.time() - t_pack0
            # exchange boundaries are host-visible on the HTTP tier:
            # fold the pack (serialize) and unpack (remote pull) sides
            # into the task's structured stats before they ship to the
            # coordinator via the task status path
            qs = getattr(res, "query_stats", None)
            if qs is not None:
                from ..exec.stats import StageStats
                ex = StageStats("exchange",
                                wall_us=int((pack_s + exchange_unpack_s)
                                            * 1e6),
                                invocations=1 + len(remote_sources),
                                rows=exchange_in_rows,
                                bytes=total_bytes)
                qs.stages["exchange"] = ex.merge(qs.stages["exchange"]) \
                    if "exchange" in qs.stages else ex
                qs.output_bytes = max(qs.output_bytes, total_bytes)
            with task.lock:
                if task.state == "ABORTED":
                    return
                task.no_more_pages = True
                task.stats = {"wallSeconds": round(wall, 4),
                              "outputRows": res.row_count,
                              "outputBytes": total_bytes}
                if qs is not None:
                    task.stats["queryStats"] = qs.to_json()
                task.state = "FINISHED"
                task.finished_at = time.time()
            task._accounted = True
            self._count("tasks_finished")
            self._count("rows_produced", res.row_count)
            self._count("exchange_bytes", total_bytes)
            if qs is not None:
                self._count("compile_us", qs.compile_us)
                self._count("execute_us", qs.stage_us("execute"))
            record_event("task_state", query_id=task.task_id,
                         state="FINISHED", rows=res.row_count)
            # (the task span itself is emitted by _run_inner's wrapper,
            # parented under the coordinator's propagated span)
            if ckey is not None:
                self.fragment_cache.put(ckey, built, res.row_count,
                                        task.stats)
            from .events import event_listeners
            event_listeners().task_completed(task.task_id, "FINISHED",
                                             res.row_count)
        except Exception as e:  # noqa: BLE001 - task failure is data
            with task.lock:
                aborted = task.state == "ABORTED"
                if not aborted:
                    task.state = "FAILED"
                    task.error = f"{type(e).__name__}: {e}"
                task.finished_at = time.time()
            # a failure AFTER coordinator abort is a routine cancellation,
            # not a task failure -- count/report what the status says
            task._accounted = True
            self._count("tasks_aborted" if aborted else "tasks_failed")
            record_event("task_state", query_id=task.task_id,
                         state="ABORTED" if aborted else "FAILED",
                         error=None if aborted else
                         f"{type(e).__name__}: {e}")
            from .events import event_listeners
            event_listeners().task_completed(
                task.task_id, "ABORTED" if aborted else "FAILED")

    def get(self, task_id: str) -> Optional[_Task]:
        with self._tasks_lock:
            return self.tasks.get(task_id)

    def results(self, task_id: str, token: int, buffer_id: int = 0):
        """-> (page_bytes|None, next_token, complete). Tokens are absolute
        per buffer; acked pages are dropped but their tokens remain
        consumed. Unknown task ids raise (the HTTP layer 404s, matching
        the task-info endpoint, so a typo'd id is distinguishable from an
        empty result)."""
        task = self.get(task_id)
        if task is None:
            raise KeyError(task_id)
        with task.lock:
            if task.moved_to is not None:
                # pages migrated during graceful drain: point the
                # consumer at the adopting peer (same absolute tokens)
                raise _MovedError(task.moved_to)
            pages = task.buffers.get(buffer_id)
            npages = 0 if pages is None else len(pages)
            first = task.first_token.get(buffer_id, 0)
            if token < first:
                # a prior consumer attempt acked past this token and the
                # pages are gone; surface it (HTTP 410) so a retried
                # consumer fails fast instead of polling forever
                raise _GoneError(
                    f"token {token} below acked prefix {first} of "
                    f"{task_id}/{buffer_id}")
            idx = token - first
            if idx < npages:
                return pages.get(idx), token + 1, False
            done = task.no_more_pages or task.state in ("FAILED", "ABORTED")
            return None, token, done and idx >= npages

    def acknowledge(self, task_id: str, token: int, buffer_id: int = 0):
        task = self.get(task_id)
        if task is None:
            return
        with task.lock:
            if task.moved_to is not None:
                return  # pages live at the peer now; acks land there
            first = task.first_token.get(buffer_id, 0)
            drop = token - first
            pages = task.buffers.get(buffer_id)
            if drop > 0 and pages is not None:
                pages.drop_prefix(drop)
                task.first_token[buffer_id] = token

    def abort(self, task_id: str):
        task = self.get(task_id)
        if task is not None:
            with task.lock:
                if task.state not in ("FINISHED", "FAILED"):
                    task.state = "ABORTED"
                    from .flight_recorder import record_event
                    record_event("task_state", query_id=task_id,
                                 state="ABORTED")
                for b in task.buffers.values():
                    b.clear()
                task.buffers = {0: task._new_buffer()}
                task.first_token = {}
                if task.finished_at is None:
                    task.finished_at = time.time()


class _Handler(BaseHTTPRequestHandler):
    server_version = "presto-tpu/0.1"
    protocol_version = "HTTP/1.1"

    # injected by TpuWorkerServer
    manager: TaskManager = None
    node_id: str = ""
    started_at: float = 0.0
    authenticator = None  # InternalAuthenticator when a secret is set
    worker_server = None  # the owning TpuWorkerServer (drain endpoints)

    def log_message(self, fmt, *args):  # quiet
        pass

    def _authorized(self) -> bool:
        """InternalAuthenticationFilter analog: with a cluster secret
        configured, every endpoint requires a valid internal bearer."""
        from .auth import authorize_request
        return authorize_request(self, self.authenticator, self._send_json)

    def _send_json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_bytes(self, body: bytes, headers: Dict[str, str], code=200):
        self.send_response(code)
        if "Content-Type" not in headers:
            self.send_header("Content-Type", "application/x-presto-pages")
        for k, v in headers.items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _failpoint_gate(self, site: str) -> bool:
        """Evaluate a server-side failpoint; False when this request was
        already answered (injected error -> 500) or deliberately severed
        (drop_conn -> socket closed without a response, the shape a
        crashed peer leaves behind)."""
        from .metrics import record_suppressed
        try:
            failpoints.hit(site)
        except failpoints.InjectedConnDrop:
            self.close_connection = True
            try:
                self.connection.close()
            except Exception as e:  # noqa: BLE001 - already severing
                record_suppressed("worker", "failpoint_drop", e)
            return False
        except Exception as e:  # noqa: BLE001 - injected server error
            self._send_json({"error": f"failpoint {site}: "
                                      f"{type(e).__name__}: {e}"}, 500)
            return False
        return True

    def _metric_families(self):
        """Worker-side metric families (shared emitter: metrics.py)."""
        from .metrics import (MetricFamily as MF, narrowing_families,
                              plan_cache_families, uptime_family)
        m = self.manager
        fams = [
            MF("presto_tpu_active_tasks", "gauge",
               "tasks in PLANNED/RUNNING state").add(m.active_task_count()),
            MF("presto_tpu_memory_reserved_bytes", "gauge",
               "admission pool reserved").add(m.memory_pool.reserved_bytes),
            MF("presto_tpu_memory_capacity_bytes", "gauge",
               "admission pool capacity").add(m.memory_pool.capacity),
            MF("presto_tpu_memory_revoked_bytes", "gauge",
               "bytes freed by spill revocation").add(
                   m.memory_pool.revoked_bytes),
            MF("presto_tpu_memory_peak_bytes", "gauge",
               "admission pool high-water mark").add(
                   m.memory_pool.peak_bytes),
            uptime_family(self.started_at, "worker"),
            MF("presto_tpu_fragment_cache_hits_total", "counter",
               "fragment result cache hits").add(m.fragment_cache.hits),
            MF("presto_tpu_fragment_cache_misses_total", "counter",
               "fragment result cache misses").add(m.fragment_cache.misses),
        ]
        with m._counters_lock:
            counters = dict(m.counters)
        for k in sorted(counters):
            if k in ("compile_us", "execute_us"):
                # export in seconds, matching the coordinator's
                # *_seconds_total families (one unit across tiers)
                fams.append(MF(
                    f"presto_tpu_{k[:-3]}_seconds_total", "counter",
                    f"lifetime task {k[:-3]} time").add(
                        counters[k] / 1e6))
                continue
            fams.append(MF(f"presto_tpu_{k}_total", "counter",
                           f"lifetime {k}").add(counters[k]))
        fams.extend(plan_cache_families())
        fams.extend(narrowing_families())
        from .metrics import (accuracy_families, batching_families,
                              datapath_families)
        fams.extend(batching_families())
        fams.extend(datapath_families())
        fams.extend(accuracy_families())
        from .metrics import (donation_families, failpoint_families,
                              flight_recorder_families,
                              histogram_families, kernel_audit_families,
                              suppressed_error_families,
                              tracing_families)
        fams.extend(suppressed_error_families())
        fams.extend(tracing_families())
        fams.extend(flight_recorder_families())
        fams.extend(kernel_audit_families())
        fams.extend(donation_families())
        fams.extend(failpoint_families())
        from .metrics import timeline_families
        fams.extend(timeline_families())
        from .metrics import lock_families
        fams.extend(lock_families())
        from .metrics import (fleet_families,
                              live_introspection_families,
                              query_history_families)
        fams.extend(query_history_families())
        # a worker's "alive" view is itself (the statement tier reports
        # its probed fleet count through the same builder); its
        # draining gauge is its own drain state
        fams.extend(live_introspection_families(workers_alive=1))
        # DRAINED is not DRAINING: once the drain completes the gauge
        # drops back to zero (matching the statement tier's count)
        fams.extend(fleet_families(
            workers_draining=1 if m.drain_state == "DRAINING" else 0))
        fams.extend(histogram_families())
        return fams

    def do_GET(self):  # noqa: N802
        if not self._authorized():
            return
        parts = [p for p in self.path.split("/") if p]
        if parts == ["v1", "info"]:
            return self._send_json({
                "nodeId": self.node_id, "nodeVersion": {"version": "0.1"},
                "environment": "tpu", "coordinator": False,
                "uptime": round(time.time() - self.started_at, 1),
                "state": "ACTIVE"})
        if parts in (["v1", "metrics"], ["v1", "info", "metrics"]):
            # Prometheus text format (PrometheusStatsReporter.cpp /
            # PrestoServer.cpp:562 registerHttpEndpoints analog);
            # /v1/info/metrics is the legacy alias. Exemplars render
            # only under negotiated OpenMetrics (classic 0.0.4 scrapers
            # reject the suffix).
            from .metrics import negotiate_exposition, render_prometheus
            om, ctype = negotiate_exposition(self.headers.get("Accept"))
            body = render_prometheus(self._metric_families(),
                                     openmetrics=om)
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if parts == ["v1", "profile"]:
            # this worker's per-kernel profile slice (the coordinator
            # pulls + merges these cluster-wide; exec/profiler.py)
            from ..exec.profiler import profile_doc
            return self._send_json(profile_doc())
        if parts == ["v1", "datapath"]:
            # this worker's per-hop data-path slice (the statement
            # tier pulls + merges these cluster-wide, same path shape;
            # exec/datapath.py)
            from ..exec.datapath import datapath_doc
            return self._send_json(datapath_doc())
        if parts == ["v1", "accuracy"]:
            # this worker's estimate-accuracy slice (the statement
            # tier pulls + stitches per-query records cluster-wide;
            # exec/accuracy.py)
            from ..exec.accuracy import accuracy_doc
            return self._send_json(accuracy_doc())
        if parts == ["v1", "timeline"]:
            # this worker's execution-timeline slice (the statement
            # tier pulls + merges these cluster-wide with processId
            # dedup; exec/timeline.py)
            from ..exec.timeline import timeline_doc
            return self._send_json(timeline_doc())
        if parts == ["v1", "history"]:
            # this process's completed-query archive slice (the
            # statement tier merges these cluster-wide like /v1/profile;
            # server/history.py)
            from .history import get_history_archive
            return self._send_json(get_history_archive().history_doc())
        if parts == ["v1", "failpoint"]:
            # live fault-injection admin surface (failpoints/): armed
            # table + lifetime hit counters + the site catalog
            return self._send_json(failpoints.admin_get_doc())
        if len(parts) == 3 and parts[:2] == ["v1", "trace"]:
            # worker-local slice of a distributed trace (the coordinator
            # serves the stitched whole; this answers "what did THIS
            # node record" when a stitch looks incomplete)
            from .tracing import get_tracer, trace_doc_of
            doc = trace_doc_of(get_tracer(), parts[2])
            return self._send_json(
                doc if doc else {"error": f"no trace {parts[2]}"},
                200 if doc else 404)
        if parts == ["v1", "worker", "drain"]:
            # live drain progress (state machine + unreplayed pages)
            return self._send_json(self.worker_server.drain_status())
        if parts == ["v1", "status"]:
            # enriched NodeStatus (the /v1/cluster fleet overview's
            # per-worker row): uptime, engine version, running tasks,
            # memory-pool occupancy. The legacy flat memory keys stay
            # for older pollers.
            m = self.manager
            pool = m.memory_pool
            return self._send_json({
                "nodeId": self.node_id,
                "nodeVersion": {"version": "presto-tpu-0.4"},
                "activeTasks": m.active_task_count(),
                "runningTasks": m.active_task_count(),
                "uptimeSeconds": round(time.time() - self.started_at, 1),
                "state": ("SHUTTING_DOWN" if m.draining
                          else "ACTIVE"),
                # the elastic-fleet state machine (/v1/cluster + ptop
                # render this; the flat `state` keeps its legacy
                # SHUTTING_DOWN spelling for older pollers)
                "fleetState": m.drain_state,
                "unreplayedPages": m.unreplayed_pages(),
                "memory": {"reservedBytes": pool.reserved_bytes,
                           "capacityBytes": pool.capacity,
                           "peakBytes": pool.peak_bytes,
                           "revokedBytes": pool.revoked_bytes},
                "memoryReservedBytes": pool.reserved_bytes,
                "memoryCapacityBytes": pool.capacity})
        if len(parts) == 3 and parts[:2] == ["v1", "task"]:
            tid, _, query = parts[2].partition("?")
            task = self.manager.get(tid)
            if task is None:
                return self._send_json({"error": "no such task"}, 404)
            if "format=spec" in query:
                # spec-shaped TaskInfo (main/tests/data/TaskInfo.json)
                from .protocol import task_info_json
                tstats = task.stats if isinstance(
                    getattr(task, "stats", None), dict) else {}
                return self._send_json(task_info_json(
                    tid, task.state, f"http://{self.node_id}",
                    self.node_id, int(time.time() * 1000),
                    rows=tstats.get("outputRows", 0),
                    query_stats=tstats.get("queryStats")))
            return self._send_json(task.info())
        if len(parts) == 4 and parts[:2] == ["v1", "task"] and \
                parts[3] == "status":
            # spec-shaped TaskStatus long-poll target (TaskResource
            # status:182 analog; the reference coordinator polls this)
            task = self.manager.get(parts[2])
            if task is None:
                return self._send_json({"error": "no such task"}, 404)
            from .protocol import task_status_json
            doc = task_status_json(
                parts[2], task.state, f"http://{self.node_id}",
                failures=[task.error] if getattr(task, "error", None)
                else None)
            if "application/x-thrift" in self.headers.get("Accept", ""):
                # the reference's optional thrift transport for the hot
                # status poll (ThriftTaskClient; JSON parse dominates at
                # cluster scale)
                from ..serde.thrift import encode_task_status
                return self._send_bytes(
                    encode_task_status(doc, parts[2]),
                    {"Content-Type": "application/x-thrift"})
            return self._send_json(doc)
        if len(parts) == 7 and parts[:2] == ["v1", "task"] and \
                parts[3] == "results" and parts[6] == "acknowledge":
            self.manager.acknowledge(parts[2], int(parts[5]), int(parts[4]))
            return self._send_json({"acknowledged": True})
        if len(parts) == 6 and parts[:2] == ["v1", "task"] and parts[3] == "results":
            if failpoints.ARMED and not self._failpoint_gate(
                    "exchange.serve"):
                return
            task_id, buffer_id, token = parts[2], int(parts[4]), int(parts[5])
            try:
                page, next_token, complete = self.manager.results(
                    task_id, token, buffer_id)
            except KeyError:
                return self._send_json({"error": f"no such task {task_id}"}, 404)
            except _GoneError as e:
                return self._send_json({"error": str(e)}, 410)
            except _MovedError as e:
                # drained-away pages: the consumer resumes its token
                # stream against the adopting peer (client.fetch_results
                # follows this header transparently)
                return self._send_bytes(b"", {
                    "X-Presto-Task-Instance-Id": task_id,
                    "X-Presto-Task-Moved": str(e),
                    "X-Presto-Page-Token": str(token),
                    "X-Presto-Page-Next-Token": str(token),
                    "X-Presto-Buffer-Complete": "false"})
            task = self.manager.get(task_id)
            if task is not None and task.state == "FAILED":
                return self._send_json({"error": task.error}, 500)
            headers = {
                "X-Presto-Task-Instance-Id": task_id,
                "X-Presto-Page-Token": str(token),
                "X-Presto-Page-Next-Token": str(next_token),
                "X-Presto-Buffer-Complete": str(complete).lower(),
            }
            return self._send_bytes(page or b"", headers)
        return self._send_json({"error": f"unknown path {self.path}"}, 404)

    def do_POST(self):  # noqa: N802
        if not self._authorized():
            return
        parts = [p for p in self.path.split("/") if p]
        if parts == ["v1", "failpoint"]:
            # arm a site ({site, spec}) or a whole schedule ({config})
            # on a RUNNING worker -- the chaos driver's live flip
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"{}")
            doc, code = failpoints.admin_post(body)
            return self._send_json(doc, code)
        if parts == ["v1", "worker", "drain"]:
            # graceful drain: refuse new tasks, finish running ones,
            # migrate remaining buffered pages ({"migrateTo": url}),
            # unannounce when empty (GracefulShutdownHandler, grown up)
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"{}")
            timeout_ms = body.get("timeoutMs")
            return self._send_json(self.worker_server.begin_drain(
                migrate_to=body.get("migrateTo"),
                timeout_s=(float(timeout_ms) / 1000.0
                           if timeout_ms is not None else None)))
        if len(parts) == 4 and parts[:2] == ["v1", "task"] and \
                parts[3] == "migrate":
            # adopt a draining peer's finished task (buffered pages at
            # their original token offsets)
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"{}")
            try:
                return self._send_json(
                    self.manager.adopt_task(parts[2], body))
            except RuntimeError as e:  # this worker is draining too
                return self._send_json({"error": str(e)}, 503)
        if len(parts) == 3 and parts[:2] == ["v1", "task"]:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"{}")
            from .tracing import TRACE_HEADER
            hdr = self.headers.get(TRACE_HEADER)
            if hdr and "traceparent" not in body:
                # header-propagated context (a reference coordinator or
                # proxy that cannot amend the body still stitches)
                body["traceparent"] = hdr
            if "outputIds" in body or "extraCredentials" in body:
                # a REFERENCE-protocol TaskUpdateRequest (the document a
                # Presto coordinator POSTs): translate its PlanFragment
                # into the engine vocabulary; unsupported constructs are
                # rejected with the PlanChecker contract (400 + reason)
                from ..plan import nodes as _N
                from ..plan.validator import validate_plan
                from .protocol import (ProtocolUnsupported,
                                       parse_task_update_request)
                try:
                    parsed = parse_task_update_request(body)
                except (ProtocolUnsupported, KeyError, TypeError) as e:
                    # malformed documents (missing fields, unresolved
                    # variables) reject with the same contract as
                    # out-of-slice constructs
                    return self._send_json(
                        {"error": f"plan not executable: "
                                  f"{type(e).__name__}: {e}",
                         "retriable": False}, 400)
                if parsed["plan"] is None:
                    return self._send_json(
                        {"error": "TaskUpdateRequest without fragment"}, 400)
                violations = validate_plan(parsed["plan"])
                if violations:
                    return self._send_json(
                        {"error": f"plan not executable: {violations}",
                         "retriable": False}, 400)
                body = {"plan": _N.to_json(parsed["plan"]),
                        # coordinator session properties flow through
                        "session": parsed["session"].get(
                            "systemProperties", {}),
                        # keep the propagated trace context (body- or
                        # header-injected above) across the translation
                        "traceparent": body.get("traceparent"),
                        "traceId": body.get("traceId")}
                sf = parsed["fragmentInfo"].get("scaleFactor")
                if sf is not None:  # else the worker's configured sf
                    body["sf"] = sf
            try:
                info = self.manager.create_or_update(parts[2], body)
            except RuntimeError as e:  # draining
                return self._send_json({"error": str(e)}, 503)
            return self._send_json(info)
        return self._send_json({"error": f"unknown path {self.path}"}, 404)

    def do_PUT(self):  # noqa: N802  graceful shutdown (worker drain)
        if not self._authorized():
            return
        parts = [p for p in self.path.split("/") if p]
        if parts == ["v1", "info", "state"]:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b'""')
            if str(body).upper().replace('"', "") == "SHUTTING_DOWN":
                # GracefulShutdownHandler: stop accepting, finish running
                self.manager.drain()
                return self._send_json({"state": "SHUTTING_DOWN"})
            return self._send_json({"error": f"unknown state {body}"}, 400)
        return self._send_json({"error": f"unknown path {self.path}"}, 404)

    def do_DELETE(self):  # noqa: N802
        if not self._authorized():
            return
        parts = [p for p in self.path.split("/") if p]
        if parts[:2] == ["v1", "failpoint"] and len(parts) in (2, 3):
            return self._send_json(failpoints.admin_delete(
                parts[2] if len(parts) == 3 else None))
        if len(parts) == 3 and parts[:2] == ["v1", "task"]:
            self.manager.abort(parts[2])
            task = self.manager.get(parts[2])
            return self._send_json(task.info() if task else {"aborted": True})
        return self._send_json({"error": f"unknown path {self.path}"}, 404)


class TpuWorkerServer:
    """HTTP worker shell (PrestoServer.cpp:493 registerHttpEndpoints
    analog). start() binds a port and serves on background threads."""

    # drain lifecycle state shared between the drain thread and the
    # HTTP handlers (tpulint C001)
    _GUARDED_BY = {"_drain_lock": ("_drain_thread", "_drain_migrated")}

    def __init__(self, port: int = 0, sf: float = 0.01, mesh=None,
                 node_id: Optional[str] = None,
                 discovery_url: Optional[str] = None,
                 announce_interval_s: float = 1.0,
                 shared_secret: Optional[str] = None,
                 task_concurrency: int = 4,
                 tls: Optional[tuple] = None):
        from .auth import make_authenticator
        # structured log correlation on the worker tier too: task
        # threads log under the propagated trace context (utils/log.py)
        from ..utils.log import ensure_log_context
        ensure_log_context()
        self.manager = TaskManager(sf=sf, mesh=mesh,
                                   task_concurrency=task_concurrency)
        self.node_id = node_id or f"tpu-worker-{uuid.uuid4().hex[:8]}"
        auth = make_authenticator(shared_secret, self.node_id)
        handler = type("BoundHandler", (_Handler,), {
            "manager": self.manager, "node_id": self.node_id,
            "started_at": time.time(), "authenticator": auth,
            "worker_server": self})
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        scheme = "http"
        if tls is not None:
            # https internal transport (internal-communication.https
            # mode; the JWT layer still authenticates peers)
            from .tls import server_context
            self.httpd.socket = server_context(*tls).wrap_socket(
                self.httpd.socket, server_side=True)
            scheme = "https"
        self.port = self.httpd.server_address[1]
        self.url = f"{scheme}://127.0.0.1:{self.port}"
        # a fresh worker on this url supersedes any drained
        # predecessor's goodbye mark (explicit-url clusters never
        # announce, so nothing else would clear it)
        from .discovery import clear_unannounced
        clear_unannounced(self.url)
        self._thread: Optional[threading.Thread] = None
        # stuck-progress watchdog (server/watchdog.py): scans this
        # manager's RUNNING tasks; disabled per task unless the session
        # property / PRESTO_TPU_STUCK_MS arms a threshold
        from .watchdog import StuckProgressWatchdog
        self._watchdog = StuckProgressWatchdog(
            self.manager._stuck_candidates, tier="worker")
        self._announcer = None
        self._shared_secret = shared_secret  # drain-migration hops
        self._drain_thread: Optional[threading.Thread] = None
        self._drain_lock = OrderedLock("worker.TpuWorkerServer._drain_lock")
        self._drain_migrated = 0
        self._stop_drain = threading.Event()  # server teardown signal
        if discovery_url:
            from .discovery import Announcer
            self._announcer = Announcer(
                discovery_url, self.node_id, self.url,
                interval_s=announce_interval_s,
                shared_secret=shared_secret)

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        self._watchdog.start()
        if self._announcer:
            self._announcer.start()
        return self

    def stop(self, unannounce: bool = True):
        self._stop_drain.set()  # release a waiting drain thread
        if self._announcer:
            self._announcer.stop(unannounce=unannounce)
        self._watchdog.stop()
        self.httpd.shutdown()
        self.httpd.server_close()

    def kill(self):
        """Ungraceful stop (a crash, not a goodbye): the HTTP server
        dies WITHOUT unannouncing, so discovery only notices when the
        announcement ages out -- the failure-detection path the chaos
        harness's kill rounds exercise, as opposed to stop()'s
        graceful goodbye."""
        self.stop(unannounce=False)

    # -- graceful drain (POST /v1/worker/drain) -------------------------

    def begin_drain(self, migrate_to: Optional[str] = None,
                    timeout_s: Optional[float] = None) -> dict:
        """Start the drain state machine (idempotent): refuse new
        tasks, announce DRAINING, then -- on a background thread --
        wait for running tasks, migrate remaining buffered pages to
        `migrate_to` (when given), and unannounce only once no
        unreplayed page remains (or the drain budget runs out: pages
        then stay served locally until consumed)."""
        with self._drain_lock:
            already = self._drain_thread is not None
            if not already:
                self.manager.drain()
                if self._announcer is not None:
                    self._announcer.set_state("DRAINING")
                t = threading.Thread(
                    target=self._drain, args=(migrate_to, timeout_s),
                    name=f"drain-{self.node_id}", daemon=True)
                self._drain_thread = t
        if already:
            return self.drain_status()
        from .metrics import record_suppressed
        if self._announcer is not None:
            try:
                # a DRAINING announcement lands NOW, not at the next
                # interval tick: placement filters react immediately.
                # (A loop-thread announcement serialized just before
                # set_state can land after this one and read ACTIVE for
                # up to one interval -- harmless: the drain refusal +
                # submit failover cover the window, and the next tick
                # re-announces DRAINING.)
                self._announcer.announce_once()
            except Exception as e:  # noqa: BLE001 - discovery may be
                # down; the drain itself must still proceed
                record_suppressed("worker", "drain_announce", e)
        t.start()
        return self.drain_status()

    def _drain(self, migrate_to: Optional[str],
               timeout_s: Optional[float]) -> None:
        from .flight_recorder import record_event
        from .metrics import record_suppressed
        if timeout_s is None:
            # the drain_timeout_ms session-property SPEC is the single
            # source of the default budget (callers override per
            # request via the body's timeoutMs)
            from ..utils.config import Session
            timeout_s = float(Session({}).get("drain_timeout_ms")) / 1e3
        budget = max(float(timeout_s), 0.0)
        deadline = time.time() + budget
        record_event("worker_drain", query_id=self.node_id,
                     phase="start", migrateTo=migrate_to)
        # 1. let running tasks finish (drain refuses only NEW ones)
        while time.time() < deadline and \
                self.manager.active_task_count() > 0:
            time.sleep(0.05)
        # 2. migrate the remaining buffered pages to the peer
        moved = 0
        try:
            if failpoints.ARMED:
                # delay/hang = a drain stuck behind a slow peer; error
                # = the migration hop dies (pages stay local + served)
                failpoints.hit("worker.drain_stall")
            if migrate_to:
                moved = self.manager.migrate_buffers(
                    migrate_to, secret=self._shared_secret)
        except Exception as e:  # noqa: BLE001 - a failed migration
            # degrades drain to serve-until-consumed, never data loss
            record_suppressed("worker", "drain_migrate", e)
        with self._drain_lock:
            self._drain_migrated = moved
        # 3. unannounce only when empty (pages all migrated/consumed).
        # The budget bounds how long we expect the fast path to take;
        # past it the node logs budget_exhausted (operator-visible) but
        # KEEPS waiting at a relaxed cadence -- a slow consumer must
        # not wedge the worker in DRAINING forever after it finally
        # drains the remainder
        exhausted = False
        while self.manager.unreplayed_pages() > 0 or \
                self.manager.active_task_count() > 0:
            if not exhausted and time.time() >= deadline:
                exhausted = True
                record_event("worker_drain", query_id=self.node_id,
                             phase="budget_exhausted",
                             migratedPages=moved,
                             unreplayedPages=self.manager
                             .unreplayed_pages())
            if self._stop_drain.wait(0.25 if exhausted else 0.05):
                return  # server stopping: leave the state as-is
        self.manager.mark_drained()
        if self._announcer is not None:
            self._announcer.stop(unannounce=True)
        # explicit-url clusters have no announcer: the process-wide
        # goodbye registry still drops this node from /v1/cluster
        # probes immediately (idempotent with the discovery DELETE)
        from .discovery import note_unannounced
        note_unannounced(self.url)
        record_event("worker_drain", query_id=self.node_id,
                     phase="complete", migratedPages=moved,
                     unreplayedPages=0)

    def drain_status(self) -> dict:
        """The drain state machine's live document (POST/GET
        /v1/worker/drain): ACTIVE | DRAINING | DRAINED plus the page
        accounting the chaos gate audits (a DRAINED worker must report
        zero unreplayed pages)."""
        m = self.manager
        with self._drain_lock:
            migrated = self._drain_migrated
        with m._counters_lock:
            adopted = m.counters.get("tasks_adopted", 0)
        return {"nodeId": self.node_id,
                "state": m.drain_state,
                "activeTasks": m.active_task_count(),
                "unreplayedPages": m.unreplayed_pages(),
                "migratedPages": migrated,
                "adoptedTasks": adopted}
