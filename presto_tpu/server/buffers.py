"""Output buffers: in-memory pages with a disk spooling tier.

Reference surface: execution/buffer/SpoolingOutputBuffer.java -- when a
task's finished result pages outgrow the memory budget, the tail
offloads to TempStorage so slow/absent consumers cannot wedge worker
memory; readers stream pages back transparently. Here: pages beyond
`memory_threshold_bytes` append to one spool file per buffer
(sequential write, seek+read on demand). The file is append-only and
reclaimed when the buffer clears (task end) -- acked pages release
MEMORY immediately, disk space at task end, matching the reference's
file-per-buffer lifecycle.
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["SpoolingOutputBuffer"]


class SpoolingOutputBuffer:
    """List-of-pages facade; entries beyond the memory budget live in
    the spool file. NOT thread-safe by itself -- callers hold the task
    lock, as they did for the plain list."""

    # tpulint C001: the caller-holds-the-task-lock contract, declared
    # (writes through self in here are the contract body; any OTHER
    # receiver mutating these fields must hold SOME lock)
    _GUARDED_BY = {"<caller>": ("_entries", "_mem_bytes",
                                "_spooled_bytes", "_file",
                                "_file_path")}

    def __init__(self, memory_threshold_bytes: int = 64 << 20,
                 spool_dir: Optional[str] = None):
        self.memory_threshold = memory_threshold_bytes
        self.spool_dir = spool_dir
        # entry: bytes (in memory) or (offset, length) in the spool file
        self._entries: List[object] = []
        self._mem_bytes = 0
        self._spooled_bytes = 0
        self._file = None
        self._file_path: Optional[str] = None

    # -- stats -------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def memory_bytes(self) -> int:
        return self._mem_bytes

    @property
    def spooled_bytes(self) -> int:
        return self._spooled_bytes

    # -- writes ------------------------------------------------------------

    def _spool_file(self):
        if self._file is None:
            fd, self._file_path = tempfile.mkstemp(
                prefix="presto-tpu-spool-", suffix=".pages",
                dir=self.spool_dir)
            self._file = os.fdopen(fd, "wb+")
        return self._file

    def append(self, page: bytes) -> None:
        if self._mem_bytes + len(page) > self.memory_threshold:
            f = self._spool_file()
            f.seek(0, os.SEEK_END)
            off = f.tell()
            f.write(page)
            f.flush()
            self._entries.append((off, len(page)))
            self._spooled_bytes += len(page)
        else:
            self._entries.append(page)
            self._mem_bytes += len(page)

    def extend(self, pages) -> None:
        for p in pages:
            self.append(p)

    # -- reads -------------------------------------------------------------

    def get(self, idx: int) -> bytes:
        e = self._entries[idx]
        if isinstance(e, tuple):
            off, length = e
            self._file.seek(off)
            return self._file.read(length)
        return e

    def snapshot(self) -> List[bytes]:
        """All pages as bytes (fragment-result-cache capture)."""
        return [self.get(i) for i in range(len(self._entries))]

    def stream_checksum(self) -> str:
        """Order-sensitive digest of the live page stream -- the
        exactly-once witness graceful drain is audited against: a
        migrated buffer must replay byte-identical pages in the same
        order (tests checksum before drain and after the redirected
        fetch)."""
        import hashlib
        h = hashlib.sha256()
        for i in range(len(self._entries)):
            page = self.get(i)
            h.update(len(page).to_bytes(8, "little"))
            h.update(page)
        return h.hexdigest()

    def export_pages(self) -> List[str]:
        """Live (un-acked) pages as base64 strings -- the drain
        migration wire format (spooled entries read back from the
        spool file; the acked prefix was already dropped and is NOT
        exported, so a consumer resuming mid-stream never re-reads)."""
        import base64
        return [base64.b64encode(self.get(i)).decode("ascii")
                for i in range(len(self._entries))]

    def restore_pages(self, encoded: List[str]) -> int:
        """Adopt a migrated page stream (inverse of export_pages) into
        this (empty) buffer; returns the byte total. Pages re-spool
        locally past the memory threshold like any append."""
        import base64
        total = 0
        for s in encoded:
            page = base64.b64decode(s)
            self.append(page)
            total += len(page)
        return total

    # -- lifecycle ---------------------------------------------------------

    def drop_prefix(self, n: int) -> None:
        """Release the first n pages (consumer acked them). Memory frees
        now; spool-file space frees at clear()."""
        for e in self._entries[:n]:
            if isinstance(e, bytes):
                self._mem_bytes -= len(e)
            else:
                self._spooled_bytes -= e[1]  # live-page stat only;
                # file space reclaims at clear()
        del self._entries[:n]

    def clear(self) -> None:
        self._entries = []
        self._mem_bytes = 0
        self._spooled_bytes = 0
        if self._file is not None:
            try:
                self._file.close()
                os.unlink(self._file_path)
            except OSError:
                pass
            self._file = None
            self._file_path = None

    def __del__(self):  # best-effort spool reclamation
        try:
            self.clear()
        except Exception:  # tpulint: disable=S001 - interpreter
            # teardown: logging/metrics modules may already be gone
            pass
