"""Internal-communication authentication: HMAC-signed JWT bearers.

Reference surface: presto-internal-communication's
InternalAuthenticationManager — when `internal-communication.shared-secret`
is configured, every coordinator<->worker / worker<->worker request
carries an HS256 JWT in the `X-Presto-Internal-Bearer` header (subject =
sender node id, ~5 min expiry), and servers reject requests whose token
is absent, tampered, or expired. The TPU cluster mirrors that contract
with a stdlib HS256 implementation (no external JWT dependency): the
same shared secret is distributed to every node (config or
PRESTO_TPU_INTERNAL_SECRET), senders mint short-lived tokens, receivers
verify with constant-time comparison.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import threading
import time
from typing import Optional

from ..utils.locks import OrderedLock

__all__ = ["AuthError", "InternalAuthenticator", "INTERNAL_BEARER_HEADER",
           "sign_jwt", "verify_jwt", "set_shared_secret",
           "get_shared_secret", "make_authenticator", "bearer_headers",
           "authorize_request"]

INTERNAL_BEARER_HEADER = "X-Presto-Internal-Bearer"

_shared_secret_lock = OrderedLock("auth._shared_secret_lock")
_shared_secret: Optional[str] = None


class AuthError(Exception):
    """Missing/invalid/expired internal bearer."""


def set_shared_secret(secret: Optional[str]) -> None:
    """Process-wide cluster secret (the config-file analog); None
    disables internal authentication."""
    global _shared_secret
    with _shared_secret_lock:
        _shared_secret = secret


def get_shared_secret() -> Optional[str]:
    with _shared_secret_lock:
        if _shared_secret is not None:
            return _shared_secret
    return os.environ.get("PRESTO_TPU_INTERNAL_SECRET") or None


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


def _signing_key(secret: str) -> bytes:
    """SHA-256 of the shared secret, matching the reference
    InternalAuthenticationManager's key derivation — tokens minted here
    validate against a Presto coordinator/worker sharing the secret."""
    return hashlib.sha256(secret.encode()).digest()


def sign_jwt(secret: str, payload: dict) -> str:
    """Compact HS256 JWS over `payload`."""
    header = _b64url(b'{"alg":"HS256","typ":"JWT"}')
    body = _b64url(json.dumps(payload, separators=(",", ":"),
                              sort_keys=True).encode())
    signing_input = f"{header}.{body}".encode()
    sig = hmac.new(_signing_key(secret), signing_input,
                   hashlib.sha256).digest()
    return f"{header}.{body}.{_b64url(sig)}"


def verify_jwt(secret: str, token: str, leeway_s: float = 30.0) -> dict:
    """Signature + expiry check; returns the payload. Raises AuthError
    on any defect (never distinguishes why, like the reference)."""
    try:
        header_b64, body_b64, sig_b64 = token.split(".")
        signing_input = f"{header_b64}.{body_b64}".encode()
        expect = hmac.new(_signing_key(secret), signing_input,
                          hashlib.sha256).digest()
        if not hmac.compare_digest(expect, _b64url_decode(sig_b64)):
            raise AuthError("bad signature")
        header = json.loads(_b64url_decode(header_b64))
        if header.get("alg") != "HS256":  # no alg-confusion downgrades
            raise AuthError("bad alg")
        payload = json.loads(_b64url_decode(body_b64))
    except AuthError:
        raise
    except Exception as e:
        raise AuthError(f"malformed token: {type(e).__name__}") from None
    exp = payload.get("exp")
    if exp is not None and time.time() > float(exp) + leeway_s:
        raise AuthError("expired")
    return payload


class InternalAuthenticator:
    """Per-node token minter + request verifier. Tokens are cached and
    re-minted at ~80% of their lifetime (the reference re-signs per
    request; caching is equivalent under the expiry contract)."""

    def __init__(self, secret: str, node_id: str = "",
                 ttl_s: float = 300.0):
        assert secret, "internal authentication needs a non-empty secret"
        self.secret = secret
        self.node_id = node_id
        self.ttl_s = ttl_s
        self._lock = OrderedLock("auth.InternalAuthenticator._lock")
        self._token: Optional[str] = None
        self._token_exp = 0.0

    def bearer(self) -> str:
        now = time.time()
        with self._lock:
            if self._token is None or now > self._token_exp - 0.2 * self.ttl_s:
                exp = now + self.ttl_s
                self._token = sign_jwt(
                    self.secret, {"sub": self.node_id, "iat": int(now),
                                  "exp": int(exp)})
                self._token_exp = exp
            return self._token

    def verify(self, token: Optional[str]) -> dict:
        if not token:
            raise AuthError("missing internal bearer")
        return verify_jwt(self.secret, token)


def make_authenticator(shared_secret: Optional[str],
                       node_id: str) -> Optional[InternalAuthenticator]:
    """The one secret-resolution idiom: an explicit secret wins, else the
    process/env-wide one; None (no secret anywhere) = open cluster."""
    secret = shared_secret if shared_secret is not None \
        else get_shared_secret()
    return InternalAuthenticator(secret, node_id) if secret else None


_default_auth: Optional[InternalAuthenticator] = None


def bearer_headers(auth: Optional[InternalAuthenticator] = None
                   ) -> dict:
    """Outbound internal-bearer header (cached tokens). With no
    authenticator given, a process-wide one is kept for the configured
    shared secret (re-created if the secret changes)."""
    global _default_auth
    if auth is None:
        secret = get_shared_secret()
        if not secret:
            _default_auth = None
            return {}
        if _default_auth is None or _default_auth.secret != secret:
            _default_auth = InternalAuthenticator(secret, "internal")
        auth = _default_auth
    return {INTERNAL_BEARER_HEADER: auth.bearer()}


def authorize_request(handler, authenticator,
                      send_json) -> bool:
    """InternalAuthenticationFilter analog for BaseHTTPRequestHandler
    subclasses: verify the bearer; on failure, DRAIN any request body
    (keep-alive framing: unread bytes would be parsed as the next
    request line) and send a 401."""
    if authenticator is None:
        return True
    try:
        authenticator.verify(
            handler.headers.get(INTERNAL_BEARER_HEADER))
        return True
    except AuthError as e:
        length = int(handler.headers.get("Content-Length", "0") or 0)
        while length > 0:
            chunk = handler.rfile.read(min(length, 1 << 16))
            if not chunk:
                break
            length -= len(chunk)
        send_json({"error": f"unauthorized: {e}"}, 401)
        return False
