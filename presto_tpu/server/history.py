"""Query history archive + in-engine perf regression sentinel.

The operational gap this closes: the engine can explain ONE query in
exhaustive detail (QueryStats, traces, flight dumps, kernel profiles)
but retains nothing once the statement TTL reaps it -- "is the cluster
slower than it was yesterday" has no in-engine answer. This module is
the cross-query, cross-run performance memory: one structured record
per completed statement (plan-cache fingerprint, the session's
kernel-mode env knobs, the QueryStats rollup, trace id, failpoint
hits, top-kernel device shares), kept in a bounded in-memory archive,
persisted as a JSONL ring under ``PRESTO_TPU_HISTORY_DIR`` (retention
caps on both file count and records per file), served at
``GET /v1/history`` (the statement tier merges worker slices exactly
like ``/v1/profile``, deduplicated by processId), and queryable as
``SELECT * FROM system.query_history``.

The SENTINEL rides every append: each FINISHED query's metric vector
(wall / execute / staged bytes / peak memory) is compared against a
rolling per-fingerprint baseline (median + MAD noise bands,
``min_samples`` warmup -- exec/perfgate.py, the same comparator the
offline bench gate runs). On breach it

  * bumps ``presto_tpu_perf_regressions_total{metric}`` (both tiers'
    ``/v1/metrics`` via :func:`query_history_families`),
  * drops a ``perf_regression`` event on the flight-recorder timeline,
  * and triggers an auto flight dump keyed by the query id, its header
    cross-linking the trace id --

so a 2x latency or staged-bytes drift is caught in-engine at the
moment it happens, not in a notebook a week later. Failed queries are
archived but never folded into baselines (a crash is not a latency
sample) and never gated (they already dumped as ``failed``).

The archive is process-wide like the flight recorder next door; swap
it with :func:`set_history_archive` in tests.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Dict, List, Optional

from ..exec.perfgate import SENTINEL_SPECS, RollingBaseline
from ..utils.locks import OrderedLock

__all__ = ["QueryHistoryArchive", "get_history_archive",
           "set_history_archive", "history_totals",
           "perf_regression_totals", "merge_history_docs",
           "cluster_history_doc", "HISTORY_DIR_ENV"]

HISTORY_DIR_ENV = "PRESTO_TPU_HISTORY_DIR"

# one id per process (the cluster merge's dedup key, like the
# profiler's): two server shells over one process fold their shared
# archive exactly once
_PROCESS_ID = None


def _process_id() -> str:
    global _PROCESS_ID
    if _PROCESS_ID is None:
        import uuid
        _PROCESS_ID = uuid.uuid4().hex
    return _PROCESS_ID


# -- process-lifetime counters (survive archive swaps; /v1/metrics) -----

_COUNTERS_LOCK = OrderedLock("history._COUNTERS_LOCK")
_RECORDS_TOTAL = {"count": 0}
_REGRESSIONS_TOTAL: Dict[str, int] = {}  # metric -> breaches


def history_totals() -> Dict[str, int]:
    with _COUNTERS_LOCK:
        return {"records": _RECORDS_TOTAL["count"]}


def perf_regression_totals() -> Dict[str, int]:
    """{metric: lifetime breach count} -- the
    ``presto_tpu_perf_regressions_total`` source."""
    with _COUNTERS_LOCK:
        return dict(_REGRESSIONS_TOTAL)


def _count_record() -> None:
    with _COUNTERS_LOCK:
        _RECORDS_TOTAL["count"] += 1


def _count_regression(metric: str) -> None:
    with _COUNTERS_LOCK:
        _REGRESSIONS_TOTAL[metric] = _REGRESSIONS_TOTAL.get(metric, 0) + 1


def _kernel_mode_envs() -> Dict[str, str]:
    """The session's kernel-mode env knobs as armed for this process
    (exec.plan_cache.KERNEL_MODE_ENVS -- the same list the plan cache
    keys executables by, so a record says which kernel forms its
    numbers were measured under)."""
    from ..exec.plan_cache import KERNEL_MODE_ENVS
    return {name: os.environ.get(name, default)
            for name, default in KERNEL_MODE_ENVS}


def _fingerprint_of(kernels: List[str], text: str,
                    kernel_mode: Dict[str, str],
                    session: Optional[dict] = None) -> str:
    """The baseline key: the executed plan-cache fingerprints when the
    profiler attributed any (the plan identity, immune to whitespace /
    literal formatting), else the collapsed statement text -- both
    salted with the kernel-mode envs (a PRESTO_TPU_NARROW=0 A/B run
    baselines separately instead of alarming against the narrow form)
    AND the session's scale factor: the text fallback would otherwise
    merge sf=0.01 and sf=1.0 runs of the same SQL into one baseline
    and page on the ~100x wall of a legitimate workload change."""
    basis = ",".join(kernels) if kernels else \
        " ".join(text.lower().split())
    mode = "|".join(f"{k}={v}" for k, v in sorted(kernel_mode.items()))
    sf = str((session or {}).get("sf", ""))
    return hashlib.sha256(
        f"{basis}#{mode}#sf={sf}".encode()).hexdigest()[:16]


class QueryHistoryArchive:
    """Bounded completed-query archive + the regression sentinel.

    ``capacity`` bounds the in-memory record list (oldest out).
    Persistence (when a directory is configured): records append to
    ``history-<n>.jsonl``, rotating at ``max_file_records`` lines and
    deleting the oldest file beyond ``max_files`` -- a JSONL ring whose
    total footprint is capped at ``max_files * max_file_records``
    records regardless of uptime. ``load()`` replays the ring into the
    archive AND the baselines (without re-firing alarms), so the
    performance memory survives a restart.
    """

    # query threads append; request handlers snapshot. The persistence
    # ring's rotation state rides its OWN lock so file I/O (a slow or
    # full disk) never stalls /v1/metrics and /v1/history readers of
    # the in-memory archive.
    _GUARDED_BY = {"_lock": ("_records", "_batch_fp_counts"),
                   "_plock": ("_file_index", "_file_lines")}

    def __init__(self, capacity: int = 512,
                 history_dir: Optional[str] = None,
                 max_file_records: int = 256, max_files: int = 8,
                 baseline: Optional[RollingBaseline] = None,
                 sentinel: bool = True):
        self.capacity = max(1, int(capacity))
        self.history_dir = history_dir if history_dir is not None \
            else (os.environ.get(HISTORY_DIR_ENV) or None)
        self.max_file_records = max(1, int(max_file_records))
        self.max_files = max(1, int(max_files))
        self.sentinel = bool(sentinel)
        self.baseline = baseline or RollingBaseline()
        self._records: List[dict] = []
        # batchFingerprint -> archived-record count, maintained on
        # append/evict so the batching executor's per-submission
        # hotness seed is O(1) instead of an O(n) scan under _lock
        self._batch_fp_counts: Dict[str, int] = {}
        self._file_index = 0
        self._file_lines = 0
        self._lock = OrderedLock("history.QueryHistoryArchive._lock")
        self._plock = OrderedLock("history.QueryHistoryArchive._plock")
        if self.history_dir:
            self.load()

    # -- record construction -------------------------------------------

    @staticmethod
    def record_of(query_id: str, state: str, user: str, text: str,
                  wall_ms: float, trace_id: str,
                  query_stats=None, session: Optional[dict] = None
                  ) -> dict:
        """Build one archive record from a terminal statement. Pure
        assembly over already-collected telemetry (QueryStats, the
        profiler's query->fingerprint attribution, the flight ring's
        failpoint events) -- never raises on partial inputs: a record
        with zeros beats no record."""
        qs = query_stats
        staging = qs.stages.get("staging") if qs is not None else None
        stats = {
            "wall_us": int(wall_ms * 1000),
            "compile_us": int(qs.compile_us) if qs is not None else 0,
            "execute_us": int(qs.stage_us("execute"))
            if qs is not None else 0,
            "staging_us": int(qs.stage_us("staging"))
            if qs is not None else 0,
            "staged_bytes": int(staging.bytes) if staging is not None
            else 0,
            "narrowed_bytes_saved": int(
                (qs.counters if qs is not None else {}).get(
                    "narrowed_bytes_saved", 0)),
            # dispatches that paid XLA compile (plan-cache misses /
            # adaptive reruns): a warm fingerprint retracing again is
            # itself a regression signal
            "retraces": int(qs.compile_us > 0) if qs is not None else 0,
            "spill_bytes": int(
                (qs.counters if qs is not None else {}).get(
                    "spill_bytes", 0)),
            "peak_memory_bytes": int(qs.peak_memory_bytes)
            if qs is not None else 0,
            "output_rows": int(qs.output_rows) if qs is not None else 0,
            "output_bytes": int(qs.output_bytes) if qs is not None else 0,
        }
        # estimate-accuracy aggregates (exec/accuracy.py): the numeric
        # worst q-error joins the sentinel's stats dict (so the perf
        # gate's max_q_error band fires on estimate DRIFT per
        # fingerprint before latency moves), and the per-node rows +
        # named verdict ride the record -- this archive is the
        # per-(fingerprint, plan-node) feedback store ROADMAP item
        # 2(c)'s estimate seeding reads
        accuracy_rows: List[dict] = []
        misestimated = ""
        max_q = 0.0
        try:
            from ..exec.accuracy import (direction_of,
                                         misestimate_verdict, q_error)
            acc = qs.accuracy if qs is not None else {}
            for node in sorted(acc):
                r = acc[node]
                q = q_error(r.est, r.actual)
                row = r.to_json()
                row["qError"] = round(q, 4) if q is not None else None
                row["direction"] = direction_of(r.est, r.actual)
                accuracy_rows.append(row)
                if q is not None and q > max_q:
                    max_q = q
            v = misestimate_verdict(acc) if acc else None
            if v is not None and not v["withinBand"]:
                misestimated = v["node"]
        except Exception as e:  # noqa: BLE001 - a record without
            # accuracy attribution still archives; count the gap
            from .metrics import record_suppressed
            record_suppressed("history", "accuracy_snapshot", e)
        stats["max_q_error"] = round(max_q, 4)
        kernels: List[str] = []
        top: List[dict] = []
        try:
            from ..exec.profiler import (profile_for_query,
                                         query_fingerprints)
            kernels = query_fingerprints(query_id)
            rows = profile_for_query(query_id, top=3)
            total = sum(int(r.get("device_us", 0)) for r in rows) or 1
            top = [{"fingerprint": r["fingerprint"],
                    "device_us": int(r.get("device_us", 0)),
                    "share": round(int(r.get("device_us", 0)) / total, 4)}
                   for r in rows]
        except Exception as e:  # noqa: BLE001 - a record without kernel
            # attribution still archives; count the gap
            from .metrics import record_suppressed
            record_suppressed("history", "profiler_snapshot", e)
        failpoint_hits = 0
        try:
            from .flight_recorder import get_flight_recorder
            failpoint_hits = sum(
                1 for e in get_flight_recorder().events(kind="failpoint")
                if e.get("trace") == trace_id)
        except Exception as e:  # noqa: BLE001 - same contract as above
            from .metrics import record_suppressed
            record_suppressed("history", "failpoint_scan", e)
        kernel_mode = _kernel_mode_envs()
        return {
            "queryId": str(query_id),
            "state": str(state),
            "user": str(user),
            "query": str(text)[:200],
            "tsUs": int(time.time() * 1_000_000),
            "fingerprint": _fingerprint_of(kernels, text, kernel_mode,
                                           session=session),
            "kernels": kernels,
            "kernelModeEnvs": kernel_mode,
            "traceId": str(trace_id),
            "stats": stats,
            "failpointHits": failpoint_hits,
            "topKernels": top,
            "accuracy": accuracy_rows,
            "misestimatedNode": misestimated,
            "session": {k: str(v) for k, v in (session or {}).items()
                        if k in ("sf", "failpoints")},
            "regressions": [],
        }

    # -- append + sentinel ---------------------------------------------

    def add(self, record: dict) -> List[dict]:
        """Archive one completed-query record; run the sentinel on
        FINISHED queries. Returns the breach verdicts (already counted
        + flight-recorded + dumped). Never raises: this runs on the
        statement tier's terminal seam."""
        try:
            return self._add_inner(record)
        except Exception as e:  # noqa: BLE001 - history is telemetry;
            # losing a record must not fail the query's terminal path
            from .metrics import record_suppressed
            record_suppressed("history", "add", e)
            return []

    def _add_inner(self, record: dict) -> List[dict]:
        breaches: List[dict] = []
        with self._lock:
            if self.sentinel and record.get("state") == "FINISHED":
                breaches = self.baseline.observe(
                    record["fingerprint"], dict(record["stats"]))
                record["regressions"] = [b["metric"] for b in breaches]
        # alarms BEFORE the record becomes visible: anything polling
        # the archive (tests, dashboards) may rely on "record present
        # implies its regressions are already counted/dumped"
        if breaches:
            self._raise_alarms(record, breaches)
        with self._lock:
            self._records.append(record)
            self._count_batch_fp_locked(record, +1)
            self._evict_over_capacity_locked()
        self._persist(record)
        _count_record()
        return breaches

    def _raise_alarms(self, record: dict, breaches: List[dict]) -> None:
        """The breach surfaces: metric counter + flight event per
        breached metric, one auto flight dump per query (the dump's
        header cross-links the trace so the waterfall is one click
        away)."""
        from .flight_recorder import get_flight_recorder, record_event
        for b in breaches:
            _count_regression(b["metric"])
            record_event("perf_regression", query_id=record["queryId"],
                         metric=b["metric"], value=b["value"],
                         median=b["median"], band=b["band"],
                         fingerprint=record["fingerprint"],
                         trace=record["traceId"])
        try:
            get_flight_recorder().maybe_dump(
                record["queryId"], "perf_regression",
                extra={"traceId": record["traceId"],
                       "fingerprint": record["fingerprint"],
                       "regressions": ",".join(
                           b["metric"] for b in breaches),
                       "query": record["query"]})
        except Exception as e:  # noqa: BLE001 - the alarm already
            # counted; a dump miss is telemetry loss, not a failure
            from .metrics import record_suppressed
            record_suppressed("history", "regression_dump", e)

    # -- persistence: the JSONL ring -----------------------------------

    def _ring_files(self) -> List[str]:
        """Ring files oldest-first (index order; names are zero-padded
        so lexical == numeric)."""
        try:
            names = sorted(n for n in os.listdir(self.history_dir)
                           if n.startswith("history-")
                           and n.endswith(".jsonl"))
        except OSError:
            return []
        return [os.path.join(self.history_dir, n) for n in names]

    def _persist(self, record: dict) -> None:
        """Append one record line to the ring (under the persistence
        lock only -- archive readers never wait on disk). Rotation: a
        fresh file every max_file_records lines, oldest file deleted
        beyond max_files. Best-effort -- a full disk must not fail the
        query's terminal path (counted)."""
        if not self.history_dir:
            return
        try:
            with self._plock:
                os.makedirs(self.history_dir, exist_ok=True)
                if self._file_lines >= self.max_file_records:
                    self._file_index += 1
                    self._file_lines = 0
                path = os.path.join(
                    self.history_dir,
                    f"history-{self._file_index:08d}.jsonl")
                with open(path, "a") as f:
                    f.write(json.dumps(record, default=str) + "\n")
                self._file_lines += 1
            files = self._ring_files()
            for stale in files[: max(0, len(files) - self.max_files)]:
                try:
                    os.remove(stale)
                except OSError:
                    continue  # raced another evictor / already gone
        except Exception as e:  # noqa: BLE001 - persistence is
            # best-effort; the in-memory archive still has the record
            from .metrics import record_suppressed
            record_suppressed("history", "persist", e)

    def load(self) -> int:
        """Replay the ring into the archive + baselines (no alarms:
        these samples already fired theirs when live). Returns the
        record count loaded. Called from __init__ when a directory is
        configured; safe on an empty/absent one."""
        loaded: List[dict] = []
        files = self._ring_files()
        for path in files:
            try:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            doc = json.loads(line)
                        except ValueError:
                            continue  # torn tail line of a crashed write
                        if isinstance(doc, dict) and "queryId" in doc:
                            loaded.append(doc)
            except OSError as e:
                from .metrics import record_suppressed
                record_suppressed("history", "load", e)
        loaded = loaded[-self.capacity:]
        with self._lock:
            for doc in loaded:
                self._records.append(doc)
                self._count_batch_fp_locked(doc, +1)
                if doc.get("state") == "FINISHED" and \
                        isinstance(doc.get("stats"), dict):
                    self.baseline.warm(str(doc.get("fingerprint", "")),
                                       {k: float(v) for k, v in
                                        doc["stats"].items()
                                        if isinstance(v, (int, float))})
            self._evict_over_capacity_locked()
        if files:
            with self._plock:
                # resume appends on the newest ring file
                last = os.path.basename(files[-1])
                try:
                    self._file_index = int(last[len("history-"):-6])
                except ValueError:
                    self._file_index = len(files)
                try:
                    with open(files[-1], "rb") as f:
                        data = f.read()
                    self._file_lines = data.count(b"\n")
                    if data and not data.endswith(b"\n"):
                        # torn tail of a crashed mid-write: terminate
                        # it so the next append starts a FRESH line
                        # instead of gluing onto (and losing) both
                        with open(files[-1], "ab") as f:
                            f.write(b"\n")
                        self._file_lines += 1
                except OSError:
                    self._file_lines = 0
        return len(loaded)

    # -- views ----------------------------------------------------------

    def records(self, fingerprint: Optional[str] = None,
                limit: Optional[int] = None) -> List[dict]:
        """Newest-first snapshot, optionally filtered by fingerprint."""
        with self._lock:
            snap = list(self._records)
        snap.reverse()
        if fingerprint:
            snap = [r for r in snap if r.get("fingerprint") == fingerprint]
        if limit is not None:
            snap = snap[: max(0, int(limit))]
        return snap

    def _count_batch_fp_locked(self, record: dict, delta: int) -> None:
        """Maintain the batchFingerprint counter (caller holds _lock)."""
        fp = record.get("batchFingerprint")
        if not fp:
            return
        n = self._batch_fp_counts.get(fp, 0) + delta
        if n > 0:
            self._batch_fp_counts[fp] = n
        else:
            self._batch_fp_counts.pop(fp, None)

    def _evict_over_capacity_locked(self) -> None:
        """Drop the oldest records past capacity (caller holds _lock),
        keeping the batchFingerprint counter exact."""
        over = len(self._records) - self.capacity
        if over > 0:
            for r in self._records[:over]:
                self._count_batch_fp_locked(r, -1)
            del self._records[:over]

    def batch_fingerprint_count(self, fingerprint: str) -> int:
        """How many archived records carry this batch-template
        fingerprint (exec/batching.py seeds its formation-window
        hotness from here, so a dashboard fingerprint is hot from the
        first poll after a restart -- the archive reloads from its
        JSONL ring). O(1): the counter is maintained on append/evict,
        this runs per batchable submission."""
        with self._lock:
            return self._batch_fp_counts.get(fingerprint, 0)

    def size(self) -> int:
        with self._lock:
            return len(self._records)

    def history_doc(self) -> dict:
        """This process's /v1/history slice."""
        return {"processId": _process_id(),
                "records": self.records()}


def merge_history_docs(docs: List[dict], capacity: int = 512
                       ) -> List[dict]:
    """Fold per-process /v1/history slices into one newest-first record
    list. Slices sharing a processId count once (two server shells over
    one process serve the same archive -- the in-process test
    topology), and records dedup by queryId (a query the coordinator
    archived is not re-counted from a worker that also saw it)."""
    # M001: every input slice is itself a retention-capped archive
    # dump, and the merged list truncates to `capacity` below
    _BOUNDED_BY = {"seen_queries": "union of retention-capped "
                                   "archive slices",
                   "out": "truncated to capacity on return"}
    seen_processes = set()
    seen_queries = set()
    out: List[dict] = []
    for doc in docs:
        pid = doc.get("processId") or f"anon-{id(doc):x}"
        if pid in seen_processes:
            continue
        seen_processes.add(pid)
        for r in doc.get("records") or ():
            if not isinstance(r, dict):
                continue
            qid = r.get("queryId")
            if qid in seen_queries:
                continue
            seen_queries.add(qid)
            out.append(r)
    out.sort(key=lambda r: (-int(r.get("tsUs", 0)),
                            str(r.get("queryId", ""))))
    return out[:capacity]


def cluster_history_doc(worker_urls=(), timeout: float = 3.0) -> dict:
    """The statement tier's cluster-merged GET /v1/history: this
    process's slice plus every reachable worker's, merged newest-first
    (the shared best-effort pull: client.pull_worker_docs)."""
    from .client import pull_worker_docs
    archive = get_history_archive()
    pulled, workers_seen = pull_worker_docs(
        worker_urls, timeout, lambda c: c.history(), "history")
    docs = [archive.history_doc(), *pulled]
    return {"processId": _process_id(), "cluster": True,
            "workersPulled": workers_seen,
            "records": merge_history_docs(docs, capacity=archive.capacity)}


_archive: Optional[QueryHistoryArchive] = None
_archive_lock = OrderedLock("history._archive_lock")


def get_history_archive() -> QueryHistoryArchive:
    """The process archive (created on first use -- always on, like
    the flight recorder)."""
    global _archive
    if _archive is None:
        with _archive_lock:
            if _archive is None:
                _archive = QueryHistoryArchive()
    return _archive


def set_history_archive(archive: Optional[QueryHistoryArchive]) -> None:
    """Swap the process archive (tests redirect the ring directory and
    shrink sentinel warmup); None resets to a fresh default on next
    use."""
    global _archive
    with _archive_lock:
        _archive = archive
