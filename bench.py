#!/usr/bin/env python
"""Benchmark: TPC-H q1 (BASELINE.md config 1) on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value        = rows/sec through the full q1 pipeline (filter + project +
               8-aggregate group-by over 6M*SF lineitem rows), steady
               state, data resident in HBM (the reference measures its
               operator pipelines the same way -- in-memory pages,
               BenchmarkSuite.java:32 / HandTpchQuery1.java).
vs_baseline  = speedup vs a single-core numpy columnar implementation of
               the same query on this host (stand-in for the reference's
               per-worker Java operator pipeline, which publishes no
               absolute numbers -- BASELINE.md "published == {}").

Env knobs: BENCH_SF (default 1.0), BENCH_ITERS (default 5).
"""

import json
import os
import sys
import time

import numpy as np


def _numpy_q1(cols, cutoff):
    """Single-core columnar oracle/baseline of q1."""
    m = cols["shipdate"] <= cutoff
    rf = cols["returnflag"][m]
    ls = cols["linestatus"][m]
    qty = cols["quantity"][m]
    price = cols["extendedprice"][m]
    disc = cols["discount"][m]
    tax = cols["tax"][m]
    key = np.char.add(rf.astype(str), ls.astype(str))
    uniq, inv = np.unique(key, return_inverse=True)
    disc_price = price * (100 - disc)
    charge = disc_price * (100 + tax)
    out = {}
    for i, k in enumerate(uniq):
        g = inv == i
        out[k] = (qty[g].sum(), price[g].sum(), disc_price[g].sum(),
                  charge[g].sum(), g.sum())
    return out


def _watchdog_main() -> int:
    """Parent mode: run the benchmark in a child process; if the child
    produces no output within BENCH_INIT_TIMEOUT + runtime allowance
    (the remote-TPU relay outage blocks backend init indefinitely --
    observed in round 1; see tests/conftest.py), kill it and re-run on
    pure CPU with the TPU plugin's site hook stripped."""
    import subprocess
    import sys

    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", "240"))
    run_timeout = float(os.environ.get("BENCH_RUN_TIMEOUT", "1800"))
    errors = []

    def run(extra_env, timeout, probe=False):
        env = dict(os.environ)
        env["BENCH_CHILD"] = "1"
        if probe:
            env["BENCH_PROBE"] = "1"
        env.update(extra_env)
        try:
            p = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               capture_output=True, text=True,
                               timeout=timeout, env=env)
            line = [l for l in p.stdout.splitlines() if l.startswith("{")]
            if not line:
                errors.append(f"rc={p.returncode} "
                              f"stderr={p.stderr.strip()[-400:]}")
                return None
            return line[-1]
        except subprocess.TimeoutExpired:
            errors.append(f"timed out after {timeout}s"
                          + (" (backend init probe)" if probe else ""))
            return None

    # phase 1: a cheap backend-init probe bounded by BENCH_INIT_TIMEOUT,
    # so a wedged TPU tunnel is detected without the full run allowance
    out = None
    if run({}, init_timeout, probe=True) is not None:
        # the real child re-pays backend init in its own process
        out = run({}, init_timeout + run_timeout)
    if out is None:
        out = run({"JAX_PLATFORMS": "cpu", "PYTHONPATH": "",
                   "BENCH_PLATFORM_NOTE": "cpu-fallback (tpu tunnel down)"},
                  run_timeout)
    if out is None:
        out = json.dumps({"metric": "tpch_q1_rows_per_sec", "value": 0,
                          "unit": "rows/s", "vs_baseline": 0,
                          "detail": {"error": "; ".join(errors)[-500:]}})
    print(out)
    return 0


def main():
    sf = float(os.environ.get("BENCH_SF", "1"))
    iters = int(os.environ.get("BENCH_ITERS", "5"))
    query = os.environ.get("BENCH_QUERY", "q1")  # q1 | q6

    import jax

    if os.environ.get("BENCH_GROUPBY") == "sort":
        # A/B hook: measure the retired sort-based group-id kernel
        # against the default hash-slot kernel. misc.py bound the name
        # by value at import, so patch both modules.
        from presto_tpu.ops import aggregation as _agg, misc as _misc
        _agg._group_ids = _agg._group_ids_sort
        _misc._group_ids = _agg._group_ids_sort

    platform = os.environ.get("BENCH_PLATFORM_NOTE") or \
        jax.devices()[0].platform

    if query == "q6":
        return _bench_q6(sf, iters, platform)

    from presto_tpu.connectors import tpch
    from presto_tpu.queries import Q1_COLUMNS, q1_local

    n = tpch.table_row_count("lineitem", sf)
    capacity = -(-n // 1024) * 1024

    t_gen = time.time()
    host_cols = tpch.generate_columns("lineitem", sf, Q1_COLUMNS)
    gen_s = time.time() - t_gen

    # numpy single-core baseline (one run)
    epoch = np.datetime64("1970-01-01")
    cutoff = int((np.datetime64("1998-09-02") - epoch).astype(int))
    t0 = time.time()
    _numpy_q1(host_cols, cutoff)
    numpy_s = time.time() - t0

    dt, staged_bytes = _stage_and_time(host_cols, Q1_COLUMNS, capacity,
                                       q1_local(), iters)

    rows_per_sec = n / dt
    baseline_rows_per_sec = n / numpy_s
    result = {
        "metric": f"tpch_sf{sf:g}_q1_rows_per_sec",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / baseline_rows_per_sec, 3),
        "detail": {
            "query_wall_s": round(dt, 5),
            "numpy_singlecore_wall_s": round(numpy_s, 4),
            "datagen_wall_s": round(gen_s, 2),
            "rows": n,
            "staged_mb": round(staged_bytes / 1e6, 1),
            "achieved_gb_per_s": round(staged_bytes / dt / 1e9, 1),
            "timing_fallback": _TIMING_FALLBACK,
            "platform": platform,
            "iters": iters,
        },
    }
    print(json.dumps(result))


def _stage_and_time(host_cols, columns, capacity, pipeline_fn, iters):
    """The one staging/warmup/timing harness both benchmarks share.

    Timing is done by *differencing* two windows -- ``iters`` and
    ``2*iters`` executions, each ended by a real host fetch of the
    result (``jax.device_get``).  With a remote device tunnel (the
    experimental axon platform), ``block_until_ready`` alone proved
    untrustworthy: round-1's first chip run reported a per-iteration
    time *below* the HBM roofline for the bytes the query must read,
    which is physically impossible and means the sync returned before
    execution finished.  Fetching the (tiny) result forces a full
    round-trip; differencing the two windows cancels that fixed
    latency, leaving pure per-iteration device time.
    """
    import jax

    from presto_tpu.block import batch_from_numpy
    from presto_tpu.connectors import tpch

    types = [tpch.column_type("lineitem", c) for c in columns]
    batch = jax.block_until_ready(jax.device_put(
        batch_from_numpy(types, [host_cols[c] for c in columns],
                         capacity=capacity)))
    run = jax.jit(pipeline_fn)
    jax.device_get(run(batch))  # warm-up / compile + full round trip

    def window(k):
        t0 = time.time()
        out = None
        for _ in range(k):
            out = run(batch)
        jax.device_get(out)  # real host fetch: cannot complete early
        return time.time() - t0

    t_small = window(iters)
    t_big = window(2 * iters)
    dt = (t_big - t_small) / iters
    global _TIMING_FALLBACK
    _TIMING_FALLBACK = dt <= 0
    if _TIMING_FALLBACK:  # noise floor: larger window's mean, round trip
        dt = t_big / (2 * iters)  # included -- flagged in the JSON detail
    staged_bytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(batch))
    return dt, staged_bytes


_TIMING_FALLBACK = False


def _bench_q6(sf, iters, platform):
    from presto_tpu.connectors import tpch
    from presto_tpu.queries import Q6_COLUMNS, q6_local

    n = tpch.table_row_count("lineitem", sf)
    capacity = -(-n // 1024) * 1024
    host = tpch.generate_columns("lineitem", sf, Q6_COLUMNS)
    dt, staged_bytes = _stage_and_time(host, Q6_COLUMNS, capacity,
                                       q6_local(), iters)
    print(json.dumps({
        "metric": f"tpch_sf{sf:g}_q6_rows_per_sec",
        "value": round(n / dt), "unit": "rows/s", "vs_baseline": 0,
        "detail": {"query_wall_s": round(dt, 5), "rows": n,
                   "staged_mb": round(staged_bytes / 1e6, 1),
                   "achieved_gb_per_s": round(staged_bytes / dt / 1e9, 1),
                   "timing_fallback": _TIMING_FALLBACK,
                   "platform": platform, "iters": iters}}))


if __name__ == "__main__":
    import sys
    if os.environ.get("BENCH_PROBE"):
        import jax
        jax.devices()  # blocks while the tunnel is wedged; parent times out
        print(json.dumps({"probe": "ok"}))
    elif os.environ.get("BENCH_CHILD"):
        main()
    else:
        sys.exit(_watchdog_main())
