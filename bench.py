#!/usr/bin/env python
"""Benchmark: TPC-H q1 (BASELINE.md config 1) on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value        = rows/sec through the FULL SQL front door: the official
               q1 text goes parser -> analyzer/planner -> connector-NDV
               capacity refinement -> XLA lowering -> kernels (the
               engine pipeline the reference benchmarks with
               BenchmarkSuite.java:32; its HandTpchQuery1 hand-built
               variant is reported in detail.hand_built_rows_per_sec).
vs_baseline  = speedup vs a single-core numpy columnar implementation of
               the same query on this host (stand-in for the reference's
               per-worker Java operator pipeline, which publishes no
               absolute numbers -- BASELINE.md "published == {}").

The run is only SCORING when it executed on the TPU: detail.platform
says where it ran, and detail.scoring is false on the CPU fallback (the
remote-TPU relay can be down; the watchdog retries with backoff before
giving up -- round-2's one-shot fallback recorded a meaningless CPU
number as the round artifact).

Env knobs: BENCH_SF (default 1.0), BENCH_ITERS (default 5),
BENCH_TUNNEL_RETRIES (default 4), BENCH_INIT_TIMEOUT (seconds, per
probe attempt), BENCH_QUERY (q1 | q6).
"""

import json
import os
import time

import numpy as np

# Official TPC-H q1 (spec text, dialect-adapted to this engine's
# unprefixed tpch column names -- same adaptation documented in
# queries/tpch_queries.py).
TPCH_Q1 = """
SELECT returnflag, linestatus,
       sum(quantity) AS sum_qty,
       sum(extendedprice) AS sum_base_price,
       sum(extendedprice * (1 - discount)) AS sum_disc_price,
       sum(extendedprice * (1 - discount) * (1 + tax)) AS sum_charge,
       avg(quantity) AS avg_qty,
       avg(extendedprice) AS avg_price,
       avg(discount) AS avg_disc,
       count(*) AS count_order
FROM lineitem
WHERE shipdate <= date '1998-09-02'
GROUP BY returnflag, linestatus
ORDER BY returnflag, linestatus
"""


def _numpy_q1(cols, cutoff):
    """Single-core columnar oracle/baseline of q1."""
    m = cols["shipdate"] <= cutoff
    rf = cols["returnflag"][m]
    ls = cols["linestatus"][m]
    qty = cols["quantity"][m]
    price = cols["extendedprice"][m]
    disc = cols["discount"][m]
    tax = cols["tax"][m]
    key = np.char.add(rf.astype(str), ls.astype(str))
    uniq, inv = np.unique(key, return_inverse=True)
    disc_price = price * (100 - disc)
    charge = disc_price * (100 + tax)
    out = {}
    for i, k in enumerate(uniq):
        g = inv == i
        out[k] = (qty[g].sum(), price[g].sum(), disc_price[g].sum(),
                  charge[g].sum(), g.sum())
    return out


def _watchdog_main() -> int:
    """Parent mode: run the benchmark in a child process. Backend init
    against the remote-TPU relay can hang indefinitely when the tunnel
    is down (observed rounds 1-2; see tests/conftest.py), so a cheap
    init probe bounds each attempt -- and the probe RETRIES with backoff
    (the tunnel has come back within minutes historically) before the
    run is allowed to fall back to CPU, where it is marked non-scoring."""
    import subprocess
    import sys

    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", "240"))
    run_timeout = float(os.environ.get("BENCH_RUN_TIMEOUT", "1800"))
    retries = int(os.environ.get("BENCH_TUNNEL_RETRIES", "4"))
    errors = []

    def run(extra_env, timeout, probe=False):
        env = dict(os.environ)
        env["BENCH_CHILD"] = "1"
        if probe:
            env["BENCH_PROBE"] = "1"
        env.update(extra_env)
        try:
            p = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               capture_output=True, text=True,
                               timeout=timeout, env=env)
            line = [l for l in p.stdout.splitlines() if l.startswith("{")]
            if not line:
                errors.append(f"rc={p.returncode} "
                              f"stderr={p.stderr.strip()[-400:]}")
                return None
            return line[-1]
        except subprocess.TimeoutExpired:
            errors.append(f"timed out after {timeout}s"
                          + (" (backend init probe)" if probe else ""))
            return None

    out = None
    for attempt in range(retries):
        if run({}, init_timeout, probe=True) is not None:
            # tunnel is up: the real child re-pays backend init itself
            out = run({}, init_timeout + run_timeout)
            break
        if attempt < retries - 1:
            backoff = min(60 * (2 ** attempt), 300)
            errors.append(f"probe attempt {attempt + 1}/{retries} failed; "
                          f"retrying in {backoff:.0f}s")
            time.sleep(backoff)
    if out is None:
        out = run({"JAX_PLATFORMS": "cpu", "PYTHONPATH": "",
                   "BENCH_PLATFORM_NOTE": "cpu-fallback (tpu tunnel down)"},
                  run_timeout)
    if out is None:
        out = json.dumps({"metric": "tpch_q1_rows_per_sec", "value": 0,
                          "unit": "rows/s", "vs_baseline": 0,
                          "detail": {"error": "; ".join(errors)[-500:],
                                     "scoring": False}})
    print(out)
    return 0


def main():
    sf = float(os.environ.get("BENCH_SF", "1"))
    iters = int(os.environ.get("BENCH_ITERS", "5"))
    query = os.environ.get("BENCH_QUERY", "q1")  # q1 | q6

    import jax

    platform = os.environ.get("BENCH_PLATFORM_NOTE") or \
        jax.devices()[0].platform
    scoring = not platform.startswith("cpu")

    if query == "q6":
        return _bench_q6(sf, iters, platform)

    from presto_tpu.connectors import tpch
    from presto_tpu.queries import Q1_COLUMNS, q1_local

    n = tpch.table_row_count("lineitem", sf)
    capacity = -(-n // 1024) * 1024

    t_gen = time.time()
    host_cols = tpch.generate_columns("lineitem", sf, Q1_COLUMNS)
    gen_s = time.time() - t_gen

    # numpy single-core baseline (one run)
    epoch = np.datetime64("1970-01-01")
    cutoff = int((np.datetime64("1998-09-02") - epoch).astype(int))
    t0 = time.time()
    _numpy_q1(host_cols, cutoff)
    numpy_s = time.time() - t0

    # --- SQL front door (the headline): parse/plan/refine ONCE, then
    # time the compiled engine pipeline exactly like the hand-built one
    t_plan = time.time()
    from presto_tpu.exec.planner import compile_plan
    from presto_tpu.plan.stats import refine_capacities
    from presto_tpu.sql.planner import plan_sql
    plan = refine_capacities(plan_sql(TPCH_Q1), sf)
    cp = compile_plan(plan)
    plan_s = time.time() - t_plan
    assert len(cp.scan_nodes) == 1
    scan_cols = cp.scan_nodes[0].columns
    sql_host = tpch.generate_columns("lineitem", sf, scan_cols)
    dt_sql, sql_staged_bytes = _stage_and_time(sql_host, scan_cols, capacity,
                                               cp.fn, iters, wrap_seq=True)
    sql_fallback = _TIMING_FALLBACK

    # --- hand-built plan (HandTpchQuery1 analog), for engine-overhead
    # comparison
    dt_hand, staged_bytes = _stage_and_time(host_cols, Q1_COLUMNS, capacity,
                                            q1_local(), iters)

    rows_per_sec = n / dt_sql
    baseline_rows_per_sec = n / numpy_s
    result = {
        "metric": f"tpch_sf{sf:g}_q1_rows_per_sec",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / baseline_rows_per_sec, 3),
        "detail": {
            "path": "sql-front-door (parser->planner->NDV refine->XLA)",
            "query_wall_s": round(dt_sql, 5),
            "hand_built_wall_s": round(dt_hand, 5),
            "hand_built_rows_per_sec": round(n / dt_hand),
            "plan_wall_s": round(plan_s, 3),
            "numpy_singlecore_wall_s": round(numpy_s, 4),
            "datagen_wall_s": round(gen_s, 2),
            "rows": n,
            "staged_mb": round(sql_staged_bytes / 1e6, 1),
            "achieved_gb_per_s": round(sql_staged_bytes / dt_sql / 1e9, 1),
            "hand_built_staged_mb": round(staged_bytes / 1e6, 1),
            "timing_fallback": sql_fallback or _TIMING_FALLBACK,
            "platform": platform,
            "scoring": scoring,
            "iters": iters,
        },
    }
    print(json.dumps(result))


def _stage_and_time(host_cols, columns, capacity, pipeline_fn, iters,
                    wrap_seq=False):
    """The one staging/warmup/timing harness both benchmarks share.

    Timing is done by *differencing* two windows -- ``iters`` and
    ``2*iters`` executions, each ended by a real host fetch of the
    result (``jax.device_get``).  With a remote device tunnel (the
    experimental axon platform), ``block_until_ready`` alone proved
    untrustworthy: round-1's first chip run reported a per-iteration
    time *below* the HBM roofline for the bytes the query must read,
    which is physically impossible and means the sync returned before
    execution finished.  Fetching the (tiny) result forces a full
    round-trip; differencing the two windows cancels that fixed
    latency, leaving pure per-iteration device time.

    ``wrap_seq``: pipeline_fn is a CompiledPlan.fn taking a SEQUENCE of
    scan batches (vs a single batch).
    """
    import jax

    from presto_tpu.block import batch_from_numpy
    from presto_tpu.connectors import tpch

    types = [tpch.column_type("lineitem", c) for c in columns]
    batch = jax.block_until_ready(jax.device_put(
        batch_from_numpy(types, [host_cols[c] for c in columns],
                         capacity=capacity)))
    fn = (lambda b: pipeline_fn([b])) if wrap_seq else pipeline_fn
    run = jax.jit(fn)
    warm = jax.device_get(run(batch))  # warm-up / compile + round trip
    if wrap_seq and int(np.asarray(warm[1])) != 0:
        raise RuntimeError("benchmark plan overflowed a static capacity; "
                           "timing would measure garbage")

    def window(k):
        t0 = time.time()
        out = None
        for _ in range(k):
            out = run(batch)
        jax.device_get(out)  # real host fetch: cannot complete early
        return time.time() - t0

    t_small = window(iters)
    t_big = window(2 * iters)
    dt = (t_big - t_small) / iters
    global _TIMING_FALLBACK
    _TIMING_FALLBACK = dt <= 0
    if _TIMING_FALLBACK:  # noise floor: larger window's mean, round trip
        dt = t_big / (2 * iters)  # included -- flagged in the JSON detail
    staged_bytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(batch))
    return dt, staged_bytes


_TIMING_FALLBACK = False


def _bench_q6(sf, iters, platform):
    from presto_tpu.connectors import tpch
    from presto_tpu.queries import Q6_COLUMNS, q6_local

    n = tpch.table_row_count("lineitem", sf)
    capacity = -(-n // 1024) * 1024
    host = tpch.generate_columns("lineitem", sf, Q6_COLUMNS)
    dt, staged_bytes = _stage_and_time(host, Q6_COLUMNS, capacity,
                                       q6_local(), iters)
    print(json.dumps({
        "metric": f"tpch_sf{sf:g}_q6_rows_per_sec",
        "value": round(n / dt), "unit": "rows/s", "vs_baseline": 0,
        "detail": {"query_wall_s": round(dt, 5), "rows": n,
                   "staged_mb": round(staged_bytes / 1e6, 1),
                   "achieved_gb_per_s": round(staged_bytes / dt / 1e9, 1),
                   "timing_fallback": _TIMING_FALLBACK,
                   "platform": platform,
                   "scoring": not platform.startswith("cpu"),
                   "iters": iters}}))


if __name__ == "__main__":
    import sys
    if os.environ.get("BENCH_PROBE"):
        import jax
        jax.devices()  # blocks while the tunnel is wedged; parent times out
        print(json.dumps({"probe": "ok"}))
    elif os.environ.get("BENCH_CHILD"):
        main()
    else:
        sys.exit(_watchdog_main())
