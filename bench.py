#!/usr/bin/env python
"""Benchmark: TPC-H q1 (BASELINE.md config 1) on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value        = rows/sec through the FULL SQL front door: the official
               q1 text goes parser -> analyzer/planner -> connector-NDV
               capacity refinement -> XLA lowering -> kernels (the
               engine pipeline the reference benchmarks with
               BenchmarkSuite.java:32; its HandTpchQuery1 hand-built
               variant is reported in detail.hand_built_rows_per_sec).
vs_baseline  = speedup vs a single-core numpy columnar implementation of
               the same query on this host (stand-in for the reference's
               per-worker Java operator pipeline, which publishes no
               absolute numbers -- BASELINE.md "published == {}").

The run is only SCORING when it executed on the TPU: detail.platform
says where it ran, and detail.scoring is false on the CPU fallback (the
remote-TPU relay can be down; the watchdog retries with backoff before
giving up -- round-2's one-shot fallback recorded a meaningless CPU
number as the round artifact).

Env knobs: BENCH_SF (default 1.0), BENCH_ITERS (default 5),
BENCH_TUNNEL_RETRIES (default 4), BENCH_INIT_TIMEOUT (seconds, per
probe attempt), BENCH_QUERY (q1 | q6).

`bench.py --full` is the chip-evidence mode (VERDICT round-3 item 2):
q1 + q6 + the join config (BASELINE config 2: q3, q14) + the
sorted-mode large-G group-by microbench, written as a timestamped JSON
under chip_evidence/ when (and only when) the run executed on the TPU.
Every tunnel probe -- scheduled by scripts/relay_watch.py throughout a
round -- appends an attempt record to chip_evidence/relay_attempts.log,
so a relay-down round leaves a verifiable trail of tries instead of one
silent CPU fallback.
"""

import json
import os
import time

import numpy as np

# Official TPC-H q1 (spec text, dialect-adapted to this engine's
# unprefixed tpch column names -- same adaptation documented in
# queries/tpch_queries.py).
TPCH_Q1 = """
SELECT returnflag, linestatus,
       sum(quantity) AS sum_qty,
       sum(extendedprice) AS sum_base_price,
       sum(extendedprice * (1 - discount)) AS sum_disc_price,
       sum(extendedprice * (1 - discount) * (1 + tax)) AS sum_charge,
       avg(quantity) AS avg_qty,
       avg(extendedprice) AS avg_price,
       avg(discount) AS avg_disc,
       count(*) AS count_order
FROM lineitem
WHERE shipdate <= date '1998-09-02'
GROUP BY returnflag, linestatus
ORDER BY returnflag, linestatus
"""


def _numpy_q1(cols, cutoff):
    """Single-core columnar oracle/baseline of q1."""
    m = cols["shipdate"] <= cutoff
    rf = cols["returnflag"][m]
    ls = cols["linestatus"][m]
    qty = cols["quantity"][m]
    price = cols["extendedprice"][m]
    disc = cols["discount"][m]
    tax = cols["tax"][m]
    key = np.char.add(rf.astype(str), ls.astype(str))
    uniq, inv = np.unique(key, return_inverse=True)
    disc_price = price * (100 - disc)
    charge = disc_price * (100 + tax)
    out = {}
    for i, k in enumerate(uniq):
        g = inv == i
        out[k] = (qty[g].sum(), price[g].sum(), disc_price[g].sum(),
                  charge[g].sum(), g.sum())
    return out


def _watchdog_main() -> int:
    """Parent mode: run the benchmark in a child process. Backend init
    against the remote-TPU relay can hang indefinitely when the tunnel
    is down (observed rounds 1-2; see tests/conftest.py), so a cheap
    init probe bounds each attempt -- and the probe RETRIES with backoff
    (the tunnel has come back within minutes historically) before the
    run is allowed to fall back to CPU, where it is marked non-scoring."""
    import subprocess
    import sys

    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", "240"))
    run_timeout = float(os.environ.get("BENCH_RUN_TIMEOUT", "1800"))
    retries = int(os.environ.get("BENCH_TUNNEL_RETRIES", "4"))
    errors = []

    def run(extra_env, timeout, probe=False):
        env = dict(os.environ)
        env["BENCH_CHILD"] = "1"
        if probe:
            env["BENCH_PROBE"] = "1"
        env.update(extra_env)
        try:
            p = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               capture_output=True, text=True,
                               timeout=timeout, env=env)
            line = [l for l in p.stdout.splitlines() if l.startswith("{")]
            if not line:
                errors.append(f"rc={p.returncode} "
                              f"stderr={p.stderr.strip()[-400:]}")
                return None
            return line[-1]
        except subprocess.TimeoutExpired:
            errors.append(f"timed out after {timeout}s"
                          + (" (backend init probe)" if probe else ""))
            return None

    out = None
    for attempt in range(retries):
        if run({}, init_timeout, probe=True) is not None:
            # tunnel is up: the real child re-pays backend init itself
            out = run({}, init_timeout + run_timeout)
            break
        if attempt < retries - 1:
            backoff = min(60 * (2 ** attempt), 300)
            errors.append(f"probe attempt {attempt + 1}/{retries} failed; "
                          f"retrying in {backoff:.0f}s")
            time.sleep(backoff)
    if out is None:
        out = run({"JAX_PLATFORMS": "cpu", "PYTHONPATH": "",
                   "BENCH_PLATFORM_NOTE": "cpu-fallback (tpu tunnel down)"},
                  run_timeout)
    if out is None:
        out = json.dumps({"metric": "tpch_q1_rows_per_sec", "value": 0,
                          "unit": "rows/s", "vs_baseline": 0,
                          "detail": {"error": "; ".join(errors)[-500:],
                                     "scoring": False}})
    print(out)
    return 0


EVIDENCE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "chip_evidence")
ATTEMPT_LOG = os.path.join(EVIDENCE_DIR, "relay_attempts.log")


def _log_attempt(status: str, detail: str = "") -> None:
    """One line per tunnel attempt: the per-attempt relay log the
    round-3 verdict asked for (proof capture was tried repeatedly)."""
    os.makedirs(EVIDENCE_DIR, exist_ok=True)
    ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(ATTEMPT_LOG, "a") as f:
        f.write(f"{ts} {status}{' ' + detail if detail else ''}\n")


def _full_main() -> int:
    """`--full` parent: probe the tunnel (honoring BENCH_TUNNEL_RETRIES
    unless --no-retry), then run the full suite in a child on the chip
    and persist a timestamped evidence JSON. Exit 2 when the relay is
    down -- the watcher keeps trying."""
    import subprocess
    import sys

    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", "240"))
    run_timeout = float(os.environ.get("BENCH_FULL_TIMEOUT", "3600"))
    retries = 1 if "--no-retry" in sys.argv else \
        int(os.environ.get("BENCH_TUNNEL_RETRIES", "4"))

    def child(extra_env, timeout):
        env = dict(os.environ)
        env.update(extra_env)
        try:
            p = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               capture_output=True, text=True,
                               timeout=timeout, env=env)
        except subprocess.TimeoutExpired:
            return None, f"timed out after {timeout:.0f}s"
        lines = [l for l in p.stdout.splitlines() if l.startswith("{")]
        if not lines:
            return None, f"rc={p.returncode} stderr={p.stderr[-400:]}"
        return lines[-1], ""

    up = False
    for attempt in range(retries):
        out, err = child({"BENCH_CHILD": "1", "BENCH_PROBE": "1"},
                         init_timeout)
        if out is not None:
            up = True
            break
        _log_attempt("DOWN", f"probe {attempt + 1}/{retries}: {err}")
        if attempt < retries - 1:
            time.sleep(min(60 * (2 ** attempt), 300))
    if not up:
        print(json.dumps({"metric": "full_suite", "value": 0,
                          "unit": "rows/s", "vs_baseline": 0,
                          "detail": {"scoring": False,
                                     "error": "tpu tunnel down; see "
                                              "chip_evidence/relay_attempts.log"}}))
        return 2
    _log_attempt("UP", "running full suite")
    out, err = child({"BENCH_CHILD": "1", "BENCH_FULL": "1"},
                     init_timeout + run_timeout)
    if out is None:
        _log_attempt("FAIL", err)
        print(json.dumps({"metric": "full_suite", "value": 0,
                          "unit": "rows/s", "vs_baseline": 0,
                          "detail": {"scoring": False, "error": err}}))
        return 1
    doc = json.loads(out)
    if not doc.get("detail", {}).get("scoring"):
        # probe succeeded but the backend is CPU (axon plugin absent /
        # misconfigured): NOT chip evidence -- log, don't persist
        _log_attempt("NON-SCORING",
                     doc.get("detail", {}).get("platform", "?"))
        print(out)
        return 2
    ts = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    os.makedirs(EVIDENCE_DIR, exist_ok=True)
    path = os.path.join(EVIDENCE_DIR, f"evidence_{ts}.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    _log_attempt("CAPTURED", path)
    print(out)
    return 0


def _bench_full():
    """BENCH_FULL child: every benchmark in one process (backend init
    and the staged lineitem columns are paid once)."""
    import contextlib
    import io

    import jax

    sf = float(os.environ.get("BENCH_SF", "1"))
    iters = int(os.environ.get("BENCH_ITERS", "5"))
    platform = jax.devices()[0].platform
    results = {}

    def capture(name, fn):
        buf = io.StringIO()
        t0 = time.time()
        try:
            with contextlib.redirect_stdout(buf):
                fn()
            line = [l for l in buf.getvalue().splitlines()
                    if l.startswith("{")][-1]
            results[name] = json.loads(line)
        except Exception as e:  # noqa: BLE001 -- evidence for every bench
            results[name] = {"error": f"{type(e).__name__}: {e}"[:500]}
        results[name]["bench_wall_s"] = round(time.time() - t0, 1)

    os.environ["BENCH_QUERY"] = "q1"
    capture("q1", main)
    capture("q6", lambda: _bench_q6(sf, iters, platform))
    # no capacity hints: the connector-NDV refinement pass sizes group
    # tables and join capacities (the stats-driven path the round-3
    # verdict asked to stand on its own)
    capture("q3", lambda: _bench_sql_join("q3", TPCH_Q3, sf, platform))
    capture("q14", lambda: _bench_sql_join("q14", TPCH_Q14, sf, platform))
    capture("groupby_large_g", lambda: _bench_large_g(platform, iters))
    value = results.get("q1", {}).get("value", 0)
    vsb = results.get("q1", {}).get("vs_baseline", 0)
    print(json.dumps({
        "metric": "full_suite", "value": value, "unit": "rows/s",
        "vs_baseline": vsb,
        "detail": {"platform": platform,
                   "scoring": not platform.startswith("cpu"),
                   "sf": sf,
                   "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                 time.gmtime()),
                   "benchmarks": results}}))


# Official TPC-H q3/q14 (BASELINE config 2), dialect-adapted like
# queries/tpch_queries.py (unprefixed generator columns via aliases).
TPCH_Q3 = """
SELECT l.orderkey, sum(l.extendedprice * (1 - l.discount)) AS revenue,
       o.orderdate, o.shippriority
FROM customer c
JOIN orders o ON c.custkey = o.custkey
JOIN lineitem l ON l.orderkey = o.orderkey
WHERE c.mktsegment = 'BUILDING'
  AND o.orderdate < date '1995-03-15' AND l.shipdate > date '1995-03-15'
GROUP BY l.orderkey, o.orderdate, o.shippriority
ORDER BY revenue DESC, o.orderdate
LIMIT 10
"""

TPCH_Q14 = """
SELECT 100.00 * sum(CASE WHEN p.type LIKE 'PROMO%'
                    THEN l.extendedprice * (1 - l.discount)
                    ELSE 0 END)
       / sum(l.extendedprice * (1 - l.discount)) AS promo_revenue
FROM lineitem l JOIN part p ON l.partkey = p.partkey
WHERE l.shipdate >= date '1995-09-01' AND l.shipdate < date '1995-10-01'
"""


def _bench_meta(platform):
    """Measurement-environment provenance recorded in every artifact:
    jax version, platform, the BENCH_SEED that pins data generation,
    and a run timestamp PASSED IN via BENCH_RUN_TS (the caller's clock
    -- scripts/perfgate.py must stay a pure function of its inputs, so
    nothing downstream reads one). The gate keys baselines on
    (metric, platform); the rest is for a human triaging WHY a sample
    moved (jax upgrade, reseeded data), not part of the key."""
    import jax
    return {"jax_version": getattr(jax, "__version__", "unknown"),
            "platform": platform,
            "seed": int(os.environ.get("BENCH_SEED", "0")),
            "timestamp": os.environ.get("BENCH_RUN_TS", ""),
            # pipeline-region fusion mode (exec/regions.py): =0 is the
            # per-operator A/B; artifacts must say which form ran
            "fusion": os.environ.get("PRESTO_TPU_FUSION", "1") != "0"}


def _latency_tail(run_once, runs=5):
    """p50/p99 per-query wall over `runs` invocations of `run_once` --
    the tail behavior the single-number BENCH headline has never
    captured (round-9 observability work)."""
    walls = []
    for _ in range(runs):
        t0 = time.time()
        run_once()
        walls.append(time.time() - t0)
    return {"p50_s": round(float(np.percentile(walls, 50)), 5),
            "p99_s": round(float(np.percentile(walls, 99)), 5),
            "runs": runs}


def _top_kernel_shares(top=3):
    """Top device-time kernels from the continuous profiler
    (exec/profiler.py), with each one's share of ALL profiled device
    time this process -- which kernels the benchmark actually paid."""
    from presto_tpu.exec.profiler import profile_snapshot
    rows = profile_snapshot()
    total = sum(k["device_us"] for k in rows) or 1
    return [{"fingerprint": k["fingerprint"][:12],
             "device_us": k["device_us"],
             "share": round(k["device_us"] / total, 4),
             "calls": k["calls"], "retraces": k["retraces"],
             "plan": k["label"][:100]}
            for k in rows[:top]]


def _query_telemetry(res):
    """QueryStats -> the compile/execute split the BENCH json records
    (exec/stats.py structured telemetry; None when stats are absent)."""
    qs = getattr(res, "query_stats", None)
    if qs is None:
        return None
    out = {"compile_s": round(qs.compile_us / 1e6, 3),
           "execute_s": round(qs.stage_us("execute") / 1e6, 5),
           "staging_s": round(qs.stage_us("staging") / 1e6, 5),
           "rows": qs.output_rows,
           "peak_memory_bytes": qs.peak_memory_bytes}
    comp = qs.stages.get("compile")
    if comp is not None and comp.flops:
        out["flops"] = comp.flops
        out["bytes_accessed"] = comp.bytes_accessed
    return out


def _bench_sql_join(name, sql_text, sf, platform, **hints):
    """End-to-end wall time of a join config through the SQL front door
    (plan + NDV refine + stage + execute; second run reuses the XLA
    compile cache, so run2 - run1 separates compile from execute --
    and the engine's own QueryStats now report the split directly)."""
    from presto_tpu.connectors import tpch
    from presto_tpu.sql import sql as run_sql

    n = tpch.table_row_count("lineitem", sf)
    t0 = time.time()
    res_cold = run_sql(sql_text, sf=sf, **hints)
    cold_s = time.time() - t0
    t0 = time.time()
    res = run_sql(sql_text, sf=sf, **hints)
    warm_s = time.time() - t0
    # warm-path latency tail (p50/p99) + which kernels burned the
    # device, from the continuous profiler -- the BENCH artifact now
    # records tail behavior beside the compile/execute split
    latency = _latency_tail(lambda: run_sql(sql_text, sf=sf, **hints),
                            runs=3)
    print(json.dumps({
        "metric": f"tpch_sf{sf:g}_{name}_rows_per_sec",
        "value": round(n / warm_s), "unit": "rows/s", "vs_baseline": 0,
        "detail": {"path": "sql-front-door end-to-end (incl. staging)",
                   "cold_wall_s": round(cold_s, 3),
                   "warm_wall_s": round(warm_s, 3),
                   "rows": n, "row_count": res.row_count,
                   "telemetry_cold": _query_telemetry(res_cold),
                   "telemetry_warm": _query_telemetry(res),
                   "latency_warm": latency,
                   "top_kernels": _top_kernel_shares(),
                   "platform": platform,
                   "scoring": not platform.startswith("cpu"),
                   "meta": _bench_meta(platform)}}))


def _bench_large_g(platform, iters):
    """Sorted-mode group-by (the G>64 default since round 3, never yet
    measured on a chip): N=4M rows, G=128k groups, sum(int64)."""
    import jax

    from presto_tpu import types as T
    from presto_tpu.block import batch_from_numpy
    from presto_tpu.ops.aggregation import AggSpec, group_by

    n, g = 4_000_000, 1 << 17
    rng = np.random.default_rng(0)
    keys = rng.integers(0, g, n).astype(np.int64)
    vals = rng.integers(-(10 ** 6), 10 ** 6, n).astype(np.int64)
    batch = jax.block_until_ready(jax.device_put(
        batch_from_numpy([T.BIGINT, T.BIGINT], [keys, vals], capacity=n)))
    spec = [AggSpec("sum", 1, T.BIGINT)]

    t_compile = time.time()
    run = jax.jit(lambda b: group_by(b, [0], spec, g).batch)
    jax.device_get(run(batch))
    compile_s = time.time() - t_compile

    dt, fallback = _diff_windows(run, batch, iters)
    print(json.dumps({
        "metric": "groupby_sorted_128k_rows_per_sec",
        "value": round(n / dt), "unit": "rows/s", "vs_baseline": 0,
        "detail": {"n": n, "groups": g, "wall_s": round(dt, 5),
                   "compile_s": round(compile_s, 1),
                   "timing_fallback": fallback,
                   "platform": platform,
                   "scoring": not platform.startswith("cpu")}}))


def _smallg_scatter_free() -> bool:
    from presto_tpu.ops.aggregation import _scatter_free
    return _scatter_free()


def main():
    sf = float(os.environ.get("BENCH_SF", "1"))
    iters = int(os.environ.get("BENCH_ITERS", "5"))
    query = os.environ.get("BENCH_QUERY", "q1")  # q1 | q6
    # the headline runs the documented scatter-free small-table form by
    # default (the round-2 win; with the narrow bf16 fused pool it is
    # one MXU pass for all accumulators). BENCH_SMALLG=auto restores
    # per-backend auto-selection, =scatter forces the scatter form.
    requested_form = os.environ.get("BENCH_SMALLG", "einsum")
    if requested_form != "auto":
        os.environ.setdefault("PRESTO_TPU_SMALLG", requested_form)
    narrow_on = os.environ.get("PRESTO_TPU_NARROW", "1") != "0"

    import jax

    platform = os.environ.get("BENCH_PLATFORM_NOTE") or \
        jax.devices()[0].platform
    scoring = not platform.startswith("cpu")

    if query == "q6":
        return _bench_q6(sf, iters, platform)

    from presto_tpu.connectors import tpch
    from presto_tpu.queries import Q1_COLUMNS, q1_local

    n = tpch.table_row_count("lineitem", sf)
    capacity = -(-n // 1024) * 1024

    t_gen = time.time()
    host_cols = tpch.generate_columns("lineitem", sf, Q1_COLUMNS)
    gen_s = time.time() - t_gen

    # numpy single-core baseline (one run)
    epoch = np.datetime64("1970-01-01")
    cutoff = int((np.datetime64("1998-09-02") - epoch).astype(int))
    t0 = time.time()
    _numpy_q1(host_cols, cutoff)
    numpy_s = time.time() - t0

    # --- SQL front door (the headline): parse/plan/refine ONCE, then
    # time the compiled engine pipeline exactly like the hand-built one
    t_plan = time.time()
    from presto_tpu.exec.planner import compile_plan
    from presto_tpu.plan.stats import refine_capacities
    from presto_tpu.plan.widths import annotate_widths
    from presto_tpu.sql.planner import plan_sql
    plan = refine_capacities(plan_sql(TPCH_Q1), sf)
    if narrow_on:
        # width inference (plan/widths.py): stage range-proven columns
        # at narrowed lanes -- the staged-MB delta below is the A/B
        # (PRESTO_TPU_NARROW=0 reverts)
        plan = annotate_widths(plan, sf)
    cp = compile_plan(plan)
    plan_s = time.time() - t_plan
    assert len(cp.scan_nodes) == 1
    scan_cols = cp.scan_nodes[0].columns
    sql_phys = cp.scan_nodes[0].physical_dtypes
    sql_host = tpch.generate_columns("lineitem", sf, scan_cols)
    dt_sql, sql_staged_bytes, sql_stage_s = _stage_and_time(
        sql_host, scan_cols, capacity, cp.fn, iters, wrap_seq=True,
        physical_dtypes=sql_phys)
    sql_fallback = _TIMING_FALLBACK

    # --- hand-built plan (HandTpchQuery1 analog), for engine-overhead
    # comparison -- staged with the same width inference
    hand_phys = None
    if narrow_on:
        from presto_tpu.plan.widths import infer_table_widths
        hand_phys = infer_table_widths(
            "tpch", "lineitem", Q1_COLUMNS,
            [tpch.column_type("lineitem", c) for c in Q1_COLUMNS], sf)
    dt_hand, staged_bytes, _hand_stage_s = _stage_and_time(
        host_cols, Q1_COLUMNS, capacity, q1_local(), iters,
        physical_dtypes=hand_phys)

    # fast telemetry smoke: one run_sql at sf=0.01 through the full
    # engine so every BENCH artifact carries the compile/execute split
    # (and XLA cost_analysis FLOPs) the QueryStats pipeline measures;
    # cheap and independent of the timed windows above
    from presto_tpu.sql import sql as run_sql
    telemetry_smoke = _query_telemetry(run_sql(
        TPCH_Q1, sf=0.01, session={"query_cost_analysis": True}))
    # per-query latency tail through the full front door at smoke
    # scale, plus the top-3 kernel device-time shares of this process
    # (incl. the sf-scale runs above): the perf trajectory finally
    # captures tail behavior and per-kernel attribution
    latency_smoke = _latency_tail(lambda: run_sql(TPCH_Q1, sf=0.01),
                                  runs=5)
    # donation A/B at smoke scale: per-query pool peak with the
    # materialized executor, donation ON -- the perfgate-gated
    # `peak_memory_mb` sample -- beside the donation-off peak and the
    # bytes the K006-proven donating dispatches aliased in place
    donation_smoke = _donation_smoke()
    # occupancy smoke at smoke scale: the q1 overlap fraction and
    # device-idle wall from the interval ledger (exec/timeline.py) --
    # the perfgate-gated `overlap_fraction` sample plus the bubble
    # verdict naming the hop the device waited on
    timeline_smoke = _timeline_smoke()

    rows_per_sec = n / dt_sql
    baseline_rows_per_sec = n / numpy_s
    result = {
        "metric": f"tpch_sf{sf:g}_q1_rows_per_sec",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / baseline_rows_per_sec, 3),
        "detail": {
            "path": "sql-front-door (parser->planner->NDV refine->XLA)",
            "query_wall_s": round(dt_sql, 5),
            "hand_built_wall_s": round(dt_hand, 5),
            "hand_built_rows_per_sec": round(n / dt_hand),
            "plan_wall_s": round(plan_s, 3),
            "numpy_singlecore_wall_s": round(numpy_s, 4),
            "datagen_wall_s": round(gen_s, 2),
            "rows": n,
            "staged_mb": round(sql_staged_bytes / 1e6, 1),
            "achieved_gb_per_s": round(sql_staged_bytes / dt_sql / 1e9, 1),
            # the MEASURED host->HBM staging rate (one device_put of
            # the q1 scan, synced): the perfgate-gated
            # `staging_gb_per_s` sample, the exact number ROADMAP
            # item 3's async split pipeline must raise past 1.0
            "staging_gb_per_s": round(
                sql_staged_bytes / max(sql_stage_s, 1e-9) / 1e9, 3),
            # per-hop achieved rates from the data-path waterfall
            # (exec/datapath.py; populated by the run_sql smoke runs)
            "datapath": _datapath_detail(),
            # per-query estimate-accuracy summary (exec/accuracy.py;
            # populated by the run_sql smoke runs): worst q-error and
            # the node that earned it ride every BENCH artifact
            "accuracy": _accuracy_detail(),
            "hand_built_staged_mb": round(staged_bytes / 1e6, 1),
            "timing_fallback": sql_fallback or _TIMING_FALLBACK,
            "telemetry_smoke_sf001": telemetry_smoke,
            "latency_smoke_sf001": latency_smoke,
            # proven-safe buffer donation (exec/donation.py): the gated
            # per-query peak rides top-level; the off-peak and donated
            # bytes ride the subsection for the A/B readout
            "peak_memory_mb": donation_smoke["peak_memory_mb"],
            "donation": donation_smoke,
            # execution-timeline occupancy (exec/timeline.py): the
            # gated overlap_fraction rides top-level (today's ~0 serial
            # baseline the async-ingest PR must raise) beside the
            # device-idle wall; the bubble verdict rides the subsection
            "overlap_fraction": timeline_smoke["overlap_fraction"],
            "device_idle_us": timeline_smoke["device_idle_us"],
            "timeline": timeline_smoke,
            "top_kernels": _top_kernel_shares(),
            "platform": platform,
            "scoring": scoring,
            "iters": iters,
            # which small-G group-by form ACTUALLY COMPILED for the
            # timed runs (recorded at trace time by ops/aggregation;
            # makes kernel A/Bs visible in artifacts) + what was asked
            "smallg_form": _executed_smallg_form(),
            "smallg_form_requested": requested_form,
            # narrow-width execution A/B (PRESTO_TPU_NARROW): staged_mb
            # above reflects the narrowed lanes when on
            "narrow_width_execution": narrow_on,
            "meta": _bench_meta(platform),
        },
    }
    print(json.dumps(result))


def _donation_smoke():
    """Donation A/B of q1 at smoke scale under the materialized region
    executor: per-query MemoryPool peak with buffer donation off vs on
    (strictly lower when a K006-proven donation landed), plus the HBM
    bytes the donating dispatches aliased in place of fresh outputs."""
    from presto_tpu.exec.donation import donation_totals
    from presto_tpu.exec.memory import MemoryPool
    from presto_tpu.sql import sql as run_sql
    peaks = {}
    donated = 0
    for name, sess in (("off", {"fusion": False}),
                       ("on", {"fusion": False,
                               "buffer_donation": True})):
        pool = MemoryPool(1 << 34)
        before = donation_totals()["donated_bytes"]
        run_sql(TPCH_Q1, sf=0.01, session=sess, memory_pool=pool,
                query_id=f"bench-donation-{name}")
        peaks[name] = pool.peak_bytes
        if name == "on":
            donated = donation_totals()["donated_bytes"] - before
    return {"peak_memory_mb": round(peaks["on"] / 1e6, 3),
            "peak_memory_mb_donation_off": round(peaks["off"] / 1e6, 3),
            "donated_bytes": donated}


def _timeline_smoke():
    """Occupancy readout of q1 at smoke scale from the execution
    -timeline ledger (exec/timeline.py): overlap fraction (the gated
    sample), device-idle wall, and the bubble verdict naming the hop
    the device spent that idle wall waiting on."""
    from presto_tpu.exec.timeline import bubble_verdict, occupancy
    from presto_tpu.sql import sql as run_sql
    res = run_sql(TPCH_Q1, sf=0.01, query_id="bench-timeline")
    intervals = res.query_stats.timeline.intervals
    occ = occupancy(intervals)
    if occ is None:
        return {"overlap_fraction": 0.0, "device_idle_us": 0,
                "bubble_verdict": ""}
    verdict = bubble_verdict(intervals, occ)
    return {"overlap_fraction": occ["overlapFraction"],
            "device_idle_us": occ["deviceIdleUs"],
            "device_idle_fraction": occ["deviceIdleFraction"],
            "bubble_hop": verdict["hop"] if verdict else "",
            "bubble_verdict": verdict["message"] if verdict else ""}


def _datapath_detail():
    """Per-hop byte totals + achieved GB/s from the process data-path
    ledger (exec/datapath.py) -- only hops the run exercised. The
    BENCH artifact records where the bytes went and how fast each hop
    moved them, beside the headline staging_gb_per_s."""
    from presto_tpu.exec.datapath import process_totals
    out = {}
    for hop, h in process_totals().items():
        if not h.invocations:
            continue
        rate = h.bytes / (h.wall_us / 1e6) if h.wall_us else 0.0
        out[hop] = {"bytes": h.bytes,
                    "achieved_gb_per_s": round(rate / 1e9, 3)}
    return out


def _accuracy_detail():
    """Per-unit estimate-accuracy roll-up from the process ledger
    (exec/accuracy.py) -- record/misestimate counts and the worst
    q-error per unit, so the BENCH artifact records whether the
    planner's cardinality/footprint estimates held for this run."""
    from presto_tpu.exec.accuracy import process_totals
    out = {}
    for unit, t in process_totals().items():
        if not t.get("records"):
            continue
        out[unit] = {"records": t["records"],
                     "under": t["under"], "over": t["over"],
                     "worst_q_error": round(t["worstQError"], 2),
                     "worst_node": t["worstNode"]}
    return out


def _executed_smallg_form():
    from presto_tpu.ops.aggregation import last_smallg_form
    return last_smallg_form() or (
        "einsum-MXU" if _smallg_scatter_free() else "scatter")


def _stage_and_time(host_cols, columns, capacity, pipeline_fn, iters,
                    wrap_seq=False, physical_dtypes=None):
    """The one staging/warmup/timing harness both benchmarks share.

    Timing is done by *differencing* two windows -- ``iters`` and
    ``2*iters`` executions, each ended by a real host fetch of the
    result (``jax.device_get``).  With a remote device tunnel (the
    experimental axon platform), ``block_until_ready`` alone proved
    untrustworthy: round-1's first chip run reported a per-iteration
    time *below* the HBM roofline for the bytes the query must read,
    which is physically impossible and means the sync returned before
    execution finished.  Fetching the (tiny) result forces a full
    round-trip; differencing the two windows cancels that fixed
    latency, leaving pure per-iteration device time.

    ``wrap_seq``: pipeline_fn is a CompiledPlan.fn taking a SEQUENCE of
    scan batches (vs a single batch).

    Returns (per-iteration wall, staged bytes, staging wall): the
    third value is the measured host->HBM put of the scan batch
    (synced), the denominator of the gated ``staging_gb_per_s``.
    """
    import jax

    from presto_tpu.block import batch_from_numpy
    from presto_tpu.connectors import tpch

    types = [tpch.column_type("lineitem", c) for c in columns]
    t_stage0 = time.time()
    batch = jax.block_until_ready(jax.device_put(
        batch_from_numpy(types, [host_cols[c] for c in columns],
                         capacity=capacity,
                         physical_dtypes=physical_dtypes)))
    stage_s = time.time() - t_stage0
    fn = (lambda b: pipeline_fn([b])) if wrap_seq else pipeline_fn
    run = jax.jit(fn)
    warm = jax.device_get(run(batch))  # warm-up / compile + round trip
    if wrap_seq and int(np.asarray(warm[1])) != 0:
        raise RuntimeError("benchmark plan overflowed a static capacity; "
                           "timing would measure garbage")

    global _TIMING_FALLBACK
    dt, _TIMING_FALLBACK = _diff_windows(run, batch, iters)
    staged_bytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(batch))
    return dt, staged_bytes, stage_s


def _diff_windows(run, batch, iters):
    """The one timing method every benchmark shares: time `iters` and
    `2*iters` windows (each ended by a real host fetch) and difference
    them, cancelling the fixed tunnel round-trip. Returns (dt, fallback);
    fallback=True means the differencing hit the noise floor and the
    larger window's mean (round trip included) was reported instead."""
    import jax

    def window(k):
        t0 = time.time()
        out = None
        for _ in range(k):
            out = run(batch)
        jax.device_get(out)  # real host fetch: cannot complete early
        return time.time() - t0

    t_small = window(iters)
    t_big = window(2 * iters)
    dt = (t_big - t_small) / iters
    if dt <= 0:
        return t_big / (2 * iters), True
    return dt, False


_TIMING_FALLBACK = False


def _bench_q6(sf, iters, platform):
    from presto_tpu.connectors import tpch
    from presto_tpu.queries import Q6_COLUMNS, q6_local

    n = tpch.table_row_count("lineitem", sf)
    capacity = -(-n // 1024) * 1024
    host = tpch.generate_columns("lineitem", sf, Q6_COLUMNS)
    dt, staged_bytes, stage_s = _stage_and_time(host, Q6_COLUMNS,
                                                capacity, q6_local(),
                                                iters)
    print(json.dumps({
        "metric": f"tpch_sf{sf:g}_q6_rows_per_sec",
        "value": round(n / dt), "unit": "rows/s", "vs_baseline": 0,
        "detail": {"query_wall_s": round(dt, 5), "rows": n,
                   "staged_mb": round(staged_bytes / 1e6, 1),
                   "achieved_gb_per_s": round(staged_bytes / dt / 1e9, 1),
                   "staging_gb_per_s": round(
                       staged_bytes / max(stage_s, 1e-9) / 1e9, 3),
                   "timing_fallback": _TIMING_FALLBACK,
                   "platform": platform,
                   "scoring": not platform.startswith("cpu"),
                   "iters": iters,
                   "meta": _bench_meta(platform)}}))


if __name__ == "__main__":
    import sys
    if os.environ.get("BENCH_PROBE"):
        import jax
        jax.devices()  # blocks while the tunnel is wedged; parent times out
        print(json.dumps({"probe": "ok"}))
    elif os.environ.get("BENCH_FULL"):
        _bench_full()
    elif os.environ.get("BENCH_CHILD"):
        main()
    elif "--full" in sys.argv:
        sys.exit(_full_main())
    else:
        sys.exit(_watchdog_main())
