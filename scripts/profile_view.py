#!/usr/bin/env python
"""Render a top-N per-kernel device-time report from /v1/profile.

The operator loop the continuous profiler exists for: point it at a
worker (local slice) or the statement tier (cluster-merged), get the
table that answers "which kernel is burning the device" -- total and
mean device time, share of the profiled total, calls, retraces,
rows/bytes throughput, and the kernaudit K005 footprint estimate.

  python scripts/profile_view.py http://127.0.0.1:8080        # either tier
  python scripts/profile_view.py profile.json                 # curl'd doc
  python scripts/profile_view.py URL --top 5 --json

Exit codes: 0 on success, 1 when the document carries no kernels,
2 when the endpoint/file is unreadable.
"""

import argparse
import json
import os
import sys
import urllib.request

# repo root importable regardless of invocation directory
sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def load_profile(target: str, timeout: float = 10.0) -> dict:
    """`target` is a base URL (the /v1/profile path is appended; a full
    /v1/profile URL also works) or a path to a saved JSON document."""
    if target.startswith(("http://", "https://")):
        url = target.rstrip("/")
        if not url.endswith("/v1/profile"):
            url = f"{url}/v1/profile"
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())
    with open(target) as f:
        return json.load(f)


def _fmt_us(us: int) -> str:
    if us >= 1_000_000:
        return f"{us / 1e6:.2f}s"
    if us >= 1000:
        return f"{us / 1e3:.1f}ms"
    return f"{us}us"


def _fmt_bytes(n: int) -> str:
    for bound, suffix in ((1 << 30, "GB"), (1 << 20, "MB"),
                          (1 << 10, "KB")):
        if n >= bound:
            return f"{n / bound:.1f}{suffix}"
    return f"{n}B"


def render(doc: dict, top: int = 10) -> str:
    kernels = doc.get("kernels") or []
    total_us = sum(int(k.get("device_us", 0)) for k in kernels) or 1
    scope = "cluster" if doc.get("cluster") else "process"
    lines = [f"-- top {min(top, len(kernels))} of {len(kernels)} "
             f"kernels by device time ({scope} scope"
             + (f", {doc.get('workersPulled', 0)} workers pulled"
                if doc.get("cluster") else "") + ") --"]
    header = (f"{'fingerprint':14} {'device':>9} {'share':>6} "
              f"{'calls':>6} {'mean':>9} {'retrace':>7} {'rows_out':>9} "
              f"{'bytes_in':>9} {'footprint':>9}  plan")
    lines.append(header)
    for k in kernels[:top]:
        device = int(k.get("device_us", 0))
        calls = max(int(k.get("calls", 0)), 1)
        lines.append(
            f"{k.get('fingerprint', '')[:12]:14} "
            f"{_fmt_us(device):>9} "
            f"{100.0 * device / total_us:>5.1f}% "
            f"{k.get('calls', 0):>6} "
            f"{_fmt_us(device // calls):>9} "
            f"{k.get('retraces', 0):>7} "
            f"{k.get('rows_out', 0):>9} "
            f"{_fmt_bytes(int(k.get('bytes_in', 0))):>9} "
            f"{_fmt_bytes(int(k.get('footprint_bytes', 0))):>9}  "
            f"{k.get('label', '')}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="profile_view")
    ap.add_argument("target",
                    help="worker/coordinator base URL, or a saved "
                         "/v1/profile JSON file")
    ap.add_argument("--top", type=int, default=10,
                    help="kernels to show (default 10)")
    ap.add_argument("--json", action="store_true",
                    help="emit the (top-truncated) document as JSON")
    args = ap.parse_args(argv)
    try:
        doc = load_profile(args.target)
    except Exception as e:  # noqa: BLE001 - unreachable target is the
        # signal this tool reports
        print(f"error: cannot load profile from {args.target}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    kernels = doc.get("kernels") or []
    if not kernels:
        print("no kernels profiled yet (is PRESTO_TPU_PROFILE=0, or "
              "has nothing executed?)", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps({**doc, "kernels": kernels[:args.top]},
                         indent=1, sort_keys=True))
    else:
        print(render(doc, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
