#!/usr/bin/env python
"""perfgate: the offline perf-regression gate over committed BENCH
artifacts. Run before sending a PR (fourth gate in lint_all.sh).

The same comparator the in-engine sentinel runs per query completion
(exec/perfgate.py: median + MAD noise bands) applied to the repo's
benchmark trajectory: every ``BENCH_r*.json`` is one sample of the
engine's headline metrics, ``PERF_BASELINE.json`` is the committed
sample history, and the NEWEST artifact is the candidate under gate.
A candidate whose rows/s dropped, wall grew, or staged bytes re-widened
beyond the per-metric noise band exits 1 -- the perf trajectory is no
longer only inspected by humans.

Deterministic by construction: the comparator reads no clocks and no
env, artifacts and baseline are explicit inputs, and ``--json`` output
is sorted -- two runs over identical artifacts are byte-identical
(tests pin this). Exit contract shared with tpulint/kernaudit:

  0  candidate inside every noise band
  1  regression finding(s)
  2  internal error (unreadable artifact/baseline, no artifacts)

Typical invocations::

    python scripts/perfgate.py                    # committed artifacts
    python scripts/perfgate.py --json             # machine-readable
    python scripts/perfgate.py --all              # gate every artifact
    python scripts/perfgate.py --update-baseline  # absorb the history
    python scripts/perfgate.py BENCH_r05.json my_run.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from presto_tpu.exec.perfgate import (BENCH_SPECS,  # noqa: E402
                                      compare_metrics)

JSON_SCHEMA_VERSION = 1
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "PERF_BASELINE.json")


def default_artifacts() -> List[str]:
    """The committed BENCH + LOADGEN trajectories, round order (lexical
    == round order for the zero-padded *_r0N names; loadgen artifacts
    carry the throughput-tier qps/p99_ms metrics under their own
    key)."""
    return (sorted(glob.glob(os.path.join(REPO_ROOT, "BENCH_r*.json")))
            + sorted(glob.glob(os.path.join(REPO_ROOT,
                                            "LOADGEN_r*.json"))))


def _platform(detail: dict) -> str:
    """First token of detail.platform: 'cpu-fallback (tpu tunnel down)'
    and a clean 'tpu' run must not share a baseline key."""
    return str(detail.get("platform", "unknown")).split()[0] or "unknown"


def load_artifact(path: str) -> Tuple[str, Dict[str, float], dict]:
    """One BENCH artifact -> (baseline key, metric vector, meta).
    Accepts both the driver wrapper ({"parsed": {...}}) and a raw
    bench.py output line saved as JSON. Raises ValueError on documents
    that are neither (the exit-2 path)."""
    with open(path) as f:
        doc = json.load(f)
    parsed = doc.get("parsed") if isinstance(doc, dict) else None
    if not isinstance(parsed, dict) or "metric" not in parsed:
        parsed = doc if isinstance(doc, dict) and "metric" in doc else None
    if parsed is None:
        raise ValueError(f"{path}: not a BENCH artifact "
                         f"(no 'metric'/'parsed.metric' key)")
    detail = parsed.get("detail") or {}
    key = f"{parsed['metric']}|{_platform(detail)}"
    metrics: Dict[str, float] = {}
    if isinstance(parsed.get("value"), (int, float)):
        metrics["rows_per_sec"] = float(parsed["value"])
    for name in ("query_wall_s", "staged_mb", "qps", "p99_ms",
                 "staging_gb_per_s", "peak_memory_mb"):
        v = detail.get(name)
        if isinstance(v, (int, float)):
            metrics[name] = float(v)
    meta = detail.get("meta") or {}
    return key, metrics, meta


def load_baseline(path: str) -> dict:
    """PERF_BASELINE.json -> {key: {sources: [...], samples: {metric:
    [...]}}} under "entries". An absent file is an empty baseline
    (first --update-baseline creates it); a malformed one raises for
    the exit-2 path."""
    if not os.path.exists(path):
        return {"version": JSON_SCHEMA_VERSION, "entries": {}}
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or \
            doc.get("version") != JSON_SCHEMA_VERSION or \
            not isinstance(doc.get("entries"), dict) or \
            not all(isinstance(e, dict) and
                    isinstance(e.get("samples"), dict)
                    for e in doc["entries"].values()):
        raise ValueError(f"{path}: bad baseline document "
                         f"(want version {JSON_SCHEMA_VERSION} + "
                         f"entries of {{sources, samples}})")
    return doc


def build_baseline(artifacts: List[Tuple[str, str, Dict[str, float]]],
                   timestamp: Optional[str] = None) -> dict:
    """Rebuild the baseline from artifact samples, given order
    preserved per key. Each entry records which artifact contributed
    each sample PER METRIC (``sources[m]`` parallel to ``samples[m]``
    -- per metric, not per entry, because artifacts can lack a metric:
    BENCH_r01 predates staged_mb), so the gate can exclude a
    candidate's OWN sample before comparing -- a baseline that
    contains the candidate would otherwise drag the median toward a
    sustained regression and under-detect it. The timestamp is PASSED
    IN (--timestamp / the caller's clock) -- nothing in the gate reads
    one, which is what keeps same-input runs byte-identical."""
    entries: Dict[str, dict] = {}
    for name, key, metrics in artifacts:
        per = entries.setdefault(key, {"sources": {}, "samples": {}})
        for m, v in metrics.items():
            per["samples"].setdefault(m, []).append(v)
            per["sources"].setdefault(m, []).append(name)
    doc = {"version": JSON_SCHEMA_VERSION, "entries": entries}
    if timestamp:
        doc["updated"] = timestamp
    return doc


def baseline_samples_for(entry: dict, candidate: str
                         ) -> Dict[str, List[float]]:
    """The entry's per-metric samples with the candidate artifact's own
    contribution LEFT OUT (matched by name through each metric's
    parallel sources list). An artifact absent from a metric's sources
    -- the normal fresh-run case -- gets that metric's full sample
    set."""
    sources = entry.get("sources") or {}
    samples = entry.get("samples") or {}
    out: Dict[str, List[float]] = {}
    for m, vals in samples.items():
        vals = list(vals)
        names = sources.get(m) if isinstance(sources, dict) else None
        if names and candidate in names and len(names) == len(vals):
            vals.pop(names.index(candidate))
        out[m] = vals
    return out


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="perfgate",
        description="offline perf-regression gate over BENCH artifacts "
                    "(median + MAD noise bands vs PERF_BASELINE.json)")
    p.add_argument("artifacts", nargs="*",
                   help="BENCH artifact paths, oldest..newest (default: "
                        "the repo's committed BENCH_r*.json)")
    p.add_argument("--baseline", metavar="PATH", default=DEFAULT_BASELINE,
                   help="baseline file (default PERF_BASELINE.json)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (schema-versioned, "
                        "byte-identical for identical inputs)")
    p.add_argument("--all", action="store_true",
                   help="gate EVERY artifact against the baseline, not "
                        "just the newest")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from the given artifacts "
                        "(then verify the newest against it)")
    p.add_argument("--timestamp", default=None,
                   help="stamp --update-baseline with this caller-"
                        "supplied time (the gate itself reads no clock)")
    args = p.parse_args(argv)

    # explicit paths keep the CALLER's oldest..newest order (the last
    # one is the candidate under gate); only the default glob sorts,
    # where the zero-padded BENCH_r0N names make lexical == round order
    paths = args.artifacts or default_artifacts()
    if not paths:
        print("perfgate: no BENCH artifacts found", file=sys.stderr)
        return 2
    try:
        loaded = [(os.path.basename(path), *load_artifact(path)[:2])
                  for path in paths]
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"perfgate: {type(e).__name__}: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        doc = build_baseline(loaded, timestamp=args.timestamp)
        try:
            with open(args.baseline, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
        except OSError as e:
            print(f"perfgate: cannot write baseline: {e}", file=sys.stderr)
            return 2
        baseline = doc
    else:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"perfgate: bad baseline: {e}", file=sys.stderr)
            return 2

    entries = baseline["entries"]
    if args.all:
        candidates = loaded
    elif args.artifacts:
        # explicit paths: the caller's LAST argument is the candidate
        candidates = loaded[-1:]
    else:
        # default glob: the newest artifact of EACH key gates, so the
        # BENCH trajectory and the LOADGEN throughput tier are both
        # checked in one run (one family cannot shadow the other)
        newest: Dict[str, Tuple[str, str, Dict[str, float]]] = {}
        for item in loaded:
            newest[item[1]] = item
        candidates = [item for item in loaded
                      if newest[item[1]] is item]
    findings: List[dict] = []
    unbaselined: List[str] = []
    checked = 0
    for name, key, metrics in candidates:
        entry = entries.get(key)
        if not entry:
            # a new metric/platform starts collecting history; it
            # cannot regress against nothing (reported, not failed)
            unbaselined.append(key)
            continue
        samples = baseline_samples_for(entry, name)
        checked += len([s for s in BENCH_SPECS if s.name in metrics])
        for verdict in compare_metrics(metrics, samples, BENCH_SPECS):
            findings.append({"artifact": name, "key": key, **verdict})

    if args.as_json:
        print(json.dumps({
            "version": JSON_SCHEMA_VERSION,
            "artifacts": [name for name, _, _ in loaded],
            "candidates": [name for name, _, _ in candidates],
            "baseline": os.path.basename(args.baseline),
            "metricsChecked": checked,
            "findings": findings,
            "unbaselined": sorted(unbaselined),
        }, indent=2, sort_keys=True))
    else:
        for f_ in findings:
            print(f"{f_['artifact']}: {f_['key']} {f_['metric']} "
                  f"{f_['direction']} band: {f_['value']:g} vs median "
                  f"{f_['median']:g} (band {f_['band']:g}, "
                  f"{f_['samples']} samples, ratio {f_['ratio']:g})")
        for key in sorted(unbaselined):
            print(f"note: {key} has no baseline entry "
                  f"(run --update-baseline to start its history)")
        verdict = "FAIL" if findings else "ok"
        print(f"{verdict} {len(findings)} regression(s) across "
              f"{len(candidates)} candidate artifact(s), "
              f"{checked} metric(s) checked "
              f"[{','.join(s.name for s in BENCH_SPECS)}]")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
