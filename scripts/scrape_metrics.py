#!/usr/bin/env python
"""Scrape /v1/metrics and diff counters between two scrapes.

The ops loop the metrics endpoint exists for, in script form: point it
at a coordinator or worker, and it reports counter DELTAS over the
interval (queries finished, rows/bytes produced, compile vs execute
seconds, cache hits), current gauge values, and -- for every histogram
family -- bucket-estimated p50/p95/p99 of the observations that landed
WITHIN the window, the numbers a before/after perf comparison cites.

Counter DECREASES between the two scrapes are monotonicity violations
(a restarted process, or a counter bug) and are flagged in their own
``violations`` section instead of silently diffing negative.

  python scripts/scrape_metrics.py http://127.0.0.1:8080 [--interval 5]
  python scripts/scrape_metrics.py URL --once          # one scrape, dump
  python scripts/scrape_metrics.py URL --count 3       # N diff windows

Exit codes: 0 on success, 2 when the endpoint is unreachable.
"""

import argparse
import json
import os
import re
import sys
import time
import urllib.request

# repo root importable regardless of invocation directory
sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from presto_tpu.server.metrics import (parse_prometheus,  # noqa: E402
                                       quantile_from_buckets)


def scrape(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(f"{url.rstrip('/')}/v1/metrics",
                                timeout=timeout) as r:
        return parse_prometheus(r.read().decode())


# tracer + flight-recorder health: reported as their own diff section,
# zeros INCLUDED -- "no spans recorded" and "no dumps written" are
# answers an operator pulling a trace needs to see, not absence of news
TRACING_FAMILIES = (
    "presto_tpu_trace_spans_total",
    "presto_tpu_traces_evicted_total",
    "presto_tpu_trace_spans_dropped_total",
    "presto_tpu_flight_recorder_events_total",
    "presto_tpu_flight_recorder_dumps_total",
)

# fault-injection accounting (presto_tpu/failpoints): its own section,
# zeros included -- during a chaos run "which faults fired in this
# window" is the first question, and "none" is an answer too
FAULT_FAMILY_PREFIX = "presto_tpu_failpoint"

# query-history archive + perf sentinel (server/history.py): its own
# always-present section, zeros included -- "no regressions this
# window" is the answer a deploy watch wants stated, not implied
HISTORY_FAMILIES = (
    "presto_tpu_query_history_entries",
    "presto_tpu_query_history_records_total",
    "presto_tpu_perf_regressions_total",
)

# live-cluster introspection (exec/progress.py + server/watchdog.py):
# an always-present gauge snapshot -- in-flight tasks, alive workers,
# stuck-progress firings -- so "is anything running / wedged RIGHT
# NOW" reads off the same diff as the retrospective sections
CLUSTER_FAMILIES = (
    "presto_tpu_running_tasks",
    "presto_tpu_cluster_workers_alive",
    "presto_tpu_stuck_queries_total",
)

# elastic fleet (server/discovery.py + coordinator speculation +
# resource_manager failover): its own always-present section, zeros
# included -- during a deploy/drain "how many workers joined/left/are
# draining, did speculation fire, did a coordinator fail over" is the
# first question, and "nothing moved" is an answer too
FLEET_FAMILIES = (
    "presto_tpu_fleet_workers_joined_total",
    "presto_tpu_fleet_workers_left_total",
    "presto_tpu_fleet_workers_draining",
    "presto_tpu_announce_retries_total",
    "presto_tpu_speculation_launched_total",
    "presto_tpu_speculation_wins_total",
    "presto_tpu_speculation_losses_total",
    "presto_tpu_coordinator_failovers_total",
)


# lock-order witness (utils/locks.py): its own always-present section,
# zeros included -- "0 inversions while ARMED" is the health statement
# the concurrency audit exists to make, and "0 while disarmed" must
# read differently (nobody was watching)
LOCK_FAMILIES = (
    "presto_tpu_lock_order_violations_total",
    "presto_tpu_lock_witness_armed",
)

# data-path waterfall (exec/datapath.py): its own always-present
# section, zeros included -- per-hop byte/second deltas (their ratio
# is the window's achieved B/s per hop) plus the size histogram's
# bucket-delta p50/p99. "No bytes moved on a hop this window" is an
# answer a staging-rate investigation needs stated, not implied.
DATAPATH_FAMILY_PREFIX = "presto_tpu_datapath"

# estimate-accuracy observatory (exec/accuracy.py): its own
# always-present section, zeros included -- record/misestimate counter
# deltas, the worst-q-error gauge, and the q-error histogram's
# bucket-delta p50/p95/p99. "No misestimates this window" is an answer
# an estimate-drift investigation needs stated, not implied.
ACCURACY_FAMILY_PREFIX = "presto_tpu_accuracy"
ACCURACY_FAMILIES = (
    "presto_tpu_misestimates_total",
    "presto_tpu_worst_q_error",
)
Q_ERROR_HISTOGRAM = "presto_tpu_q_error"


# proven-safe buffer donation (exec/donation.py): its own
# always-present section, zeros included -- donated dispatches, HBM
# bytes aliased in place, and donation-path fallbacks. "Donation never
# fired this window" is an answer an HBM-headroom investigation needs
# stated, not implied.
DONATION_FAMILIES = (
    "presto_tpu_donations_total",
    "presto_tpu_donated_bytes_total",
    "presto_tpu_donation_fallbacks_total",
)

# execution-timeline occupancy (exec/timeline.py): its own
# always-present section, zeros included -- interval/drop/query counter
# deltas plus the overlap-fraction and device-idle gauges. "Overlap
# stayed at zero this window" is an answer a pipeline-occupancy
# investigation needs stated, not implied.
TIMELINE_FAMILY_PREFIX = "presto_tpu_timeline"
TIMELINE_FAMILIES = (
    "presto_tpu_overlap_fraction",
    "presto_tpu_device_idle_us",
)


_LE_RE = re.compile(r'le="([^"]+)"')


def _histogram_window(before: dict, after: dict, fam: str) -> dict:
    """Per label-set window stats of one histogram family: delta
    counts per bucket between the scrapes -> estimated p50/p95/p99 of
    the interval's observations (quantile_from_buckets, the same
    arithmetic the server-side Histogram uses)."""
    out = {}
    groups = {}
    for key, val in after.get(fam + "_bucket", {}).items():
        m = _LE_RE.search(key)
        if not m:
            continue
        series = _LE_RE.sub("", key).replace(",,", ",").replace(
            "{,", "{").replace(",}", "}")
        le = m.group(1)
        prev = before.get(fam + "_bucket", {}).get(key, 0.0)
        groups.setdefault(series, []).append(
            (float("inf") if le == "+Inf" else float(le), val - prev))
    for series, buckets in groups.items():
        buckets.sort(key=lambda x: x[0])
        bounds = [b for b, _ in buckets if b != float("inf")]
        # cumulative deltas -> per-bucket deltas (clamped: a restarted
        # process yields negatives, reported as count_delta < 0)
        cums = [c for _, c in buckets]
        per = [cums[0]] + [cums[i] - cums[i - 1]
                           for i in range(1, len(cums))]
        count = cums[-1] if cums else 0.0
        doc = {"count_delta": round(count, 6)}
        if count > 0 and bounds:
            clamped = [max(c, 0.0) for c in per]
            for q, name in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                doc[name] = round(
                    quantile_from_buckets(bounds, clamped, q), 6)
        out[series if series != "{}" else ""] = doc
    return out


def diff(before: dict, after: dict) -> dict:
    """Counter deltas + gauge currents between two parsed scrapes,
    histogram window quantiles, counter-monotonicity violations, plus
    the always-present tracing/flight-recorder section."""
    out = {"counters": {}, "gauges": {}, "tracing": {}, "faults": {},
           "history": {}, "cluster": {}, "fleet": {}, "locks": {},
           "datapath": {}, "accuracy": {}, "donation": {},
           "timeline": {}, "histograms": {}, "violations": {}}
    hist_bases = set()
    for fam, samples in after.items():
        if fam.endswith("_bucket"):
            hist_bases.add(fam[: -len("_bucket")])
            continue
        base = fam.rsplit("_", 1)[0]
        if fam.endswith(("_sum", "_count")) and \
                (base + "_bucket") in after:
            continue  # folded into the histogram section
        is_counter = fam.endswith("_total")
        is_fault = fam.startswith(FAULT_FAMILY_PREFIX)
        is_datapath = fam.startswith(DATAPATH_FAMILY_PREFIX)
        is_accuracy = fam.startswith(ACCURACY_FAMILY_PREFIX) \
            or fam in ACCURACY_FAMILIES
        is_history = fam in HISTORY_FAMILIES
        is_cluster = fam in CLUSTER_FAMILIES
        is_fleet = fam in FLEET_FAMILIES
        is_locks = fam in LOCK_FAMILIES
        is_donation = fam in DONATION_FAMILIES
        is_timeline = fam.startswith(TIMELINE_FAMILY_PREFIX) \
            or fam in TIMELINE_FAMILIES
        for key, val in samples.items():
            label = fam + key
            if is_counter:
                prev = before.get(fam, {}).get(key, 0.0)
                delta = val - prev
                if delta < 0:
                    # a counter went DOWN: that is a restart or a bug,
                    # not a negative rate -- flag it, don't diff it
                    out["violations"][label] = round(delta, 6)
                    continue
                if is_fault:
                    out["faults"][label] = round(delta, 6)
                elif is_datapath:
                    # per-hop byte/second deltas, zeros included: the
                    # window's bytes/seconds ratio is the achieved B/s
                    out["datapath"][label] = round(delta, 6)
                elif is_accuracy:
                    # record + misestimate deltas, zeros included
                    out["accuracy"][label] = round(delta, 6)
                elif is_history:
                    out["history"][label] = round(delta, 6)
                elif is_fleet:
                    # membership churn / speculation / failover deltas,
                    # zeros included
                    out["fleet"][label] = round(delta, 6)
                elif is_cluster:
                    # stuck-firing delta rides the cluster section
                    out["cluster"][label] = round(delta, 6)
                elif is_locks:
                    # inversion delta, zero included: "0 new
                    # inversions" is the statement, not silence
                    out["locks"][label] = round(delta, 6)
                elif is_donation:
                    # donated dispatches / bytes / fallback deltas,
                    # zeros included
                    out["donation"][label] = round(delta, 6)
                elif is_timeline:
                    # interval/drop/query deltas, zeros included
                    out["timeline"][label] = round(delta, 6)
                elif fam in TRACING_FAMILIES:
                    out["tracing"][label] = round(delta, 6)
                elif delta:
                    out["counters"][label] = round(delta, 6)
            elif is_fault:
                # the armed gauge rides the faults section too: "3
                # faults fired, 2 still armed" reads off one block
                out["faults"][label] = round(val, 6)
            elif is_accuracy:
                # the worst-q-error gauge rides beside the misestimate
                # deltas: "0 new misestimates, worst ever 47x" reads
                # off one block
                out["accuracy"][label] = round(val, 6)
            elif is_timeline:
                # the overlap/idle gauges ride beside the interval
                # deltas: "overlap 0, device idle 31ms" reads off one
                # block
                out["timeline"][label] = round(val, 6)
            elif is_history:
                # the archive-size gauge rides the history section:
                # "N records retained, 0 regressions" reads off one block
                out["history"][label] = round(val, 6)
            elif is_fleet:
                # the draining gauge rides the fleet section: "2 left,
                # 1 still draining" reads off one block
                out["fleet"][label] = round(val, 6)
            elif is_cluster:
                # current gauge values: "what is in flight NOW" reads
                # off one block beside the stuck delta
                out["cluster"][label] = round(val, 6)
            elif is_locks:
                # the armed gauge rides beside the inversion delta so
                # the zero is qualified: watched, or unwatched
                out["locks"][label] = round(val, 6)
            else:
                out["gauges"][label] = round(val, 6)
    for base in sorted(hist_bases):
        win = _histogram_window(before, after, base)
        if not win:
            continue
        if base.startswith(DATAPATH_FAMILY_PREFIX):
            # the size histogram's bucket-delta quantiles ride the
            # datapath section beside the byte deltas (zeros included)
            out["datapath"][base] = win
        elif base == Q_ERROR_HISTOGRAM:
            # the q-error ladder's bucket-delta quantiles ride the
            # accuracy section beside the misestimate deltas
            out["accuracy"][base] = win
        else:
            out["histograms"][base] = win
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="scrape_metrics")
    ap.add_argument("url", help="coordinator or worker base URL")
    ap.add_argument("--interval", type=float, default=5.0,
                    help="seconds between the two scrapes (default 5)")
    ap.add_argument("--count", type=int, default=1,
                    help="number of diff windows to report")
    ap.add_argument("--once", action="store_true",
                    help="single scrape: dump all families, no diff")
    args = ap.parse_args(argv)

    try:
        before = scrape(args.url)
    except Exception as e:  # noqa: BLE001 - endpoint down is the signal
        print(f"error: cannot scrape {args.url}/v1/metrics: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    if args.once:
        print(json.dumps(before, indent=1, sort_keys=True))
        return 0
    for _ in range(args.count):
        time.sleep(args.interval)
        try:
            after = scrape(args.url)
        except Exception as e:  # noqa: BLE001
            print(f"error: scrape lost: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2
        print(json.dumps({"intervalSeconds": args.interval,
                          **diff(before, after)}, sort_keys=True))
        before = after
    return 0


if __name__ == "__main__":
    sys.exit(main())
