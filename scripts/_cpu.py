"""Import this FIRST in ad-hoc scripts to force CPU jax (the repo's
conftest armor, shared): the image sitecustomize registers the axon
remote-TPU plugin in every interpreter and pins jax_platforms to it;
when the relay is down any backend init hangs in a retry sleep."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
# the persistent-cache AOT loader logs a benign ERROR about the
# prefer-no-scatter/gather tuning pseudo-features on every load; keep
# the test tier readable (override via TF_CPP_MIN_LOG_LEVEL)
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
os.environ.setdefault("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] = (os.environ["XLA_FLAGS"]
                               + " --xla_force_host_platform_device_count=8").strip()
import jax

jax.config.update("jax_platforms", "cpu")
try:
    import jax._src.xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

# Persistent XLA compilation cache (works on the CPU backend too): the
# suite's cost on a 1-core runner is almost entirely compiles, so warm
# reruns of the verifier/TPC-DS tiers drop from minutes to seconds.
_cache_dir = os.environ.get(
    "PRESTO_TPU_JAX_CACHE",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".cache", "jax"))
if _cache_dir != "off":
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
