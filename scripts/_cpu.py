"""Import this FIRST in ad-hoc scripts to force CPU jax (the repo's
conftest armor, shared): the image sitecustomize registers the axon
remote-TPU plugin in every interpreter and pins jax_platforms to it;
when the relay is down any backend init hangs in a retry sleep."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] = (os.environ["XLA_FLAGS"]
                               + " --xla_force_host_platform_device_count=8").strip()
import jax

jax.config.update("jax_platforms", "cpu")
try:
    import jax._src.xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
