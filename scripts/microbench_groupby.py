"""Micro-benchmark group-by building blocks on the attached device.

Times each primitive with the two-window differencing harness bench.py
uses (real host fetch ends each window; differencing cancels the fixed
tunnel round-trip). Drives the choice of group-by kernel for the hot
path (HandTpchQuery1-style measurement discipline)."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import presto_tpu  # noqa: F401  (x64 on, before any array is created)

N = int(os.environ.get("MB_ROWS", 6_000_000))
G = int(os.environ.get("MB_GROUPS", 16))
ITERS = int(os.environ.get("MB_ITERS", 5))


def timeit(name, fn, *args):
    fn_j = jax.jit(fn)
    jax.device_get(fn_j(*args))  # compile + round trip

    def window(k):
        t0 = time.time()
        out = None
        for _ in range(k):
            out = fn_j(*args)
        jax.device_get(out)
        return time.time() - t0

    t1 = window(ITERS)
    t2 = window(2 * ITERS)
    dt = (t2 - t1) / ITERS
    if dt <= 0:
        dt = t2 / (2 * ITERS)
    print(f"{name:42s} {dt*1e3:10.2f} ms")
    return dt


def main():
    rng = np.random.default_rng(0)
    ids_np = rng.integers(0, G, N).astype(np.int32)
    v_np = rng.integers(-(10**7), 10**7, N).astype(np.int64)
    w_np = rng.integers(0, 2**63, N, dtype=np.int64).astype(np.uint64)
    ids = jax.device_put(jnp.asarray(ids_np))
    v = jax.device_put(jnp.asarray(v_np))
    w = jax.device_put(jnp.asarray(w_np))
    active = jnp.ones(N, dtype=bool)

    print(f"platform={jax.devices()[0].platform} n={N} G={G}")

    timeit("scatter_add int64 (n->G)",
           lambda i, x: jnp.zeros(G, dtype=jnp.int64).at[i].add(x), ids, v)

    timeit("sort by int32 ids (2 operands)",
           lambda i: jax.lax.sort([i, jnp.arange(N, dtype=jnp.int32)],
                                  num_keys=1), ids)

    timeit("sort by 4 uint64 words",
           lambda a: jax.lax.sort([a, a ^ jnp.uint64(1), a ^ jnp.uint64(2),
                                   a ^ jnp.uint64(3),
                                   jnp.arange(N, dtype=jnp.int32)],
                                  num_keys=4), w)

    timeit("cumsum int64", lambda x: jnp.cumsum(x), v)

    def masked_reduce_loop(i, x):
        outs = [jnp.sum(jnp.where(i == g, x, 0)) for g in range(G)]
        return jnp.stack(outs)

    timeit("masked-reduce loop (G passes)", masked_reduce_loop, ids, v)

    def onehot_matmul_limb(i, x):
        KC = 2048
        C = -(-N // KC)
        pad = C * KC - N
        i = jnp.pad(i, (0, pad), constant_values=G)  # pad -> no group
        x = jnp.pad(x, (0, pad))
        # 13-bit limbs, top limb signed: exact in f32 per chunk
        limbs = []
        rem = x
        for _ in range(4):
            limbs.append((rem & 0x1FFF).astype(jnp.float32))
            rem = rem >> 13
        limbs.append(rem.astype(jnp.float32))  # signed top (52-13*4=12 bits used)
        lm = jnp.stack(limbs, axis=1).reshape(C, KC, 5)
        i = i.reshape(C, KC)
        oh = (i[:, :, None] ==
              jnp.arange(G, dtype=jnp.int32)).astype(jnp.float32)
        part = jnp.einsum('ckg,ckl->cgl', oh, lm,
                          precision=jax.lax.Precision.HIGHEST,
                          preferred_element_type=jnp.float32)
        tot = jnp.sum(part.astype(jnp.int64), axis=0)  # (G, 5)
        scale = (jnp.int64(1) << (13 * jnp.arange(5, dtype=jnp.int64)))
        return jnp.sum(tot * scale[None, :], axis=1)

    r = jax.jit(onehot_matmul_limb)(ids, v)
    oracle = np.zeros(G, dtype=np.int64)
    np.add.at(oracle, ids_np, v_np)
    assert np.array_equal(np.asarray(r), oracle), (np.asarray(r), oracle)
    timeit("one-hot limb matmul (exact int64)", onehot_matmul_limb, ids, v)

    def seg_sum_via_sort(i, x):
        s = jax.lax.sort([i, x], num_keys=1)
        si, sx = s
        c = jnp.cumsum(sx)
        ends = jnp.searchsorted(si, jnp.arange(1, G + 1, dtype=jnp.int32)) - 1
        tot = c[jnp.clip(ends, 0, N - 1)]
        starts = jnp.concatenate([jnp.zeros(1, dtype=tot.dtype), tot[:-1]])
        return tot - starts

    r2 = jax.jit(seg_sum_via_sort)(ids, v)
    assert np.array_equal(np.asarray(r2), oracle)
    timeit("sort-by-id + cumsum segment sum", seg_sum_via_sort, ids, v)

    # the current hash-slot id kernel, isolated
    from presto_tpu.ops.aggregation import _group_ids
    from presto_tpu.block import Column
    from presto_tpu import types as T
    col = Column(v, jnp.zeros(N, dtype=bool), T.BIGINT)
    # inputs passed as jit ARGUMENTS (not closure constants) so XLA
    # cannot constant-fold any of the kernel away
    timeit("hash-slot _group_ids (1 int64 col)",
           lambda c, a: _group_ids([c], a, G), col, active)

    from presto_tpu.ops.aggregation import _group_ids_sort
    timeit("sort-based _group_ids (1 int64 col)",
           lambda c, a: _group_ids_sort([c], a, G), col, active)

    def first_occurrence_ids(words, act):
        """Candidate small-G id kernel: iteratively extract the first
        unresolved row's key, match all equal rows -- G data passes,
        zero scatters."""
        n = act.shape[0]
        rows = jnp.arange(n, dtype=jnp.int32)

        def body(state):
            g, ids = state
            unres = act & (ids < 0)
            i = jnp.min(jnp.where(unres, rows, n))
            i_safe = jnp.clip(i, 0, n - 1)
            match = unres
            for w in words:
                match = match & (w == w[i_safe])
            ids = jnp.where(match, g, ids)
            return g + 1, ids

        def cond(state):
            g, ids = state
            return (g < G) & jnp.any(act & (ids < 0))

        g, ids = jax.lax.while_loop(
            cond, body, (jnp.int32(0), jnp.full(n, -1, dtype=jnp.int32)))
        return g, ids

    ids16 = (w % jnp.uint64(G)).astype(jnp.uint64)  # 16 distinct "keys"
    timeit("first-occurrence ids (G rounds, 1 word)",
           lambda ww, a: first_occurrence_ids([ww], a), ids16, active)


def narrow_ab():
    """`--narrow-ab`: narrow-vs-wide A/B per primitive -- staged bytes
    and wall for each (staged lane dtype x kernel form) cell, so
    chip-day measurements slot straight into PERF.md. Toggles
    PRESTO_TPU_NARROW around each trace (the kernel forms are
    trace-time static) and stages the value column at int64/int32/int16
    physical lanes. All forms are exact; equality is asserted against a
    numpy oracle every cell."""
    from presto_tpu.ops.aggregation import (_limb_matmul_sum,
                                            last_smallg_form)

    rng = np.random.default_rng(0)
    ids_np = rng.integers(0, G, N).astype(np.int32)
    # int16-safe domain so every staged lane width is value-preserving
    v_np = rng.integers(-(2 ** 14), 2 ** 14, N).astype(np.int64)
    oracle = np.zeros(G, dtype=np.int64)
    np.add.at(oracle, ids_np, v_np)
    ids = jax.device_put(jnp.asarray(ids_np))

    print(f"platform={jax.devices()[0].platform} n={N} G={G} "
          f"(narrow-vs-wide A/B; oracle-checked)")
    print(f"{'cell':42s} {'staged':>10s} {'wall':>10s}")

    def cell(name, narrow, fn, *args):
        os.environ["PRESTO_TPU_NARROW"] = "1" if narrow else "0"
        # force the bf16 form for the narrow cells so the A/B is
        # kernel-vs-kernel even off-TPU (where bf16 is emulated; the
        # chip numbers are the ones PERF.md wants)
        os.environ["PRESTO_TPU_BF16"] = "1" if narrow else "0"
        from presto_tpu.ops import aggregation as _agg
        _agg._LAST_SMALLG_FORM[0] = None  # tag only THIS cell's trace
        try:
            staged = sum(int(np.asarray(a).nbytes) for a in args)
            r = np.asarray(jax.jit(fn)(*args))
            assert np.array_equal(r, oracle), name
            fn_j = jax.jit(fn)
            jax.device_get(fn_j(*args))

            def window(k):
                t0 = time.time()
                out = None
                for _ in range(k):
                    out = fn_j(*args)
                jax.device_get(out)
                return time.time() - t0

            t1, t2 = window(ITERS), window(2 * ITERS)
            dt = (t2 - t1) / ITERS
            if dt <= 0:
                dt = t2 / (2 * ITERS)
            print(f"{name:42s} {staged / 1e6:8.1f}MB {dt * 1e3:8.2f}ms"
                  f"  [{last_smallg_form()}]")
        finally:
            os.environ.pop("PRESTO_TPU_NARROW", None)
            os.environ.pop("PRESTO_TPU_BF16", None)

    for dt_name in ("int64", "int32", "int16"):
        v = jax.device_put(jnp.asarray(v_np.astype(dt_name)))
        vb = {"int64": 64, "int32": 32, "int16": 16}[dt_name]

        def scatter(i, x):
            return jnp.zeros(G, dtype=jnp.int64).at[i].add(
                x.astype(jnp.int64))

        cell(f"scatter-add ({dt_name} lanes)", False, scatter, ids, v)
        cell(f"limb matmul wide f32-HIGHEST ({dt_name})", False,
             lambda i, x: _limb_matmul_sum(i, x, G, value_bits=vb), ids, v)
        cell(f"limb matmul narrow bf16 ({dt_name})", True,
             lambda i, x: _limb_matmul_sum(i, x, G, value_bits=vb), ids, v)

    # fused cross-aggregate pool: 8 accumulators in ONE matmul vs 8
    from presto_tpu.ops.aggregation import _fused_limb_sums
    v64 = jax.device_put(jnp.asarray(v_np))

    def fused(i, x):
        return jnp.stack(_fused_limb_sums(i, [(x, 16)] * 8, G))

    def unfused(i, x):
        return jnp.stack([_limb_matmul_sum(i, x, G, value_bits=16)
                          for _ in range(8)])

    for narrow in (True, False):
        tag = "narrow-bf16" if narrow else "wide-f32"
        # force both gates so the A/B is kernel-vs-kernel off-TPU too
        # (same as cell(); on CPU bf16 is emulated -- chip numbers are
        # the ones PERF.md wants)
        os.environ["PRESTO_TPU_NARROW"] = "1" if narrow else "0"
        os.environ["PRESTO_TPU_BF16"] = "1" if narrow else "0"
        oracle8 = np.tile(oracle, (8, 1))

        def chk(fn, name):
            r = np.asarray(jax.jit(fn)(ids, v64))
            assert np.array_equal(r, oracle8), name

        chk(fused, "fused")
        chk(unfused, "unfused")
        timeit(f"8-accumulator FUSED pool ({tag})", fused, ids, v64)
        timeit(f"8-accumulator unfused ({tag})", unfused, ids, v64)
    os.environ.pop("PRESTO_TPU_NARROW", None)
    os.environ.pop("PRESTO_TPU_BF16", None)


if __name__ == "__main__":
    if "--narrow-ab" in sys.argv:
        narrow_ab()
    else:
        main()
