#!/usr/bin/env python
"""kernaudit: presto-tpu's jaxpr-level IR gate. Run before sending a PR
(tpulint checks the AST; this checks the IR XLA actually compiles).

Thin launcher over ``presto_tpu.audit.cli`` -- see that module for the
exit-code contract and DESIGN.md ("Kernel IR auditing") for the pass
catalog (K001-K005), suppression syntax (``# kernaudit: disable=K001``
on the source line an eqn traces to), and baseline policy
(``kernaudit_baseline.json``, committed empty -- fix, don't baseline).

    python scripts/kernaudit.py                  # TPC-H q1-q22 gate
    python scripts/kernaudit.py --json           # stable machine output
    python scripts/kernaudit.py --queries 1,6 --tier local
    python scripts/kernaudit.py --select K001 tests/fixtures/kernaudit/k001_bad.py
    python scripts/kernaudit.py --list-passes
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")  # the gate only traces

from presto_tpu.audit.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
