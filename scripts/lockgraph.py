#!/usr/bin/env python
"""lockgraph: the server tier's lock-acquisition-order graph as a
reviewable artifact.

Builds the same whole-program graph tpulint C002 checks (see
presto_tpu/lint/lockmodel.py for the extraction rules) and:

  * writes/refreshes the committed ``LOCK_ORDER.json`` at the repo
    root (``--update``), so every PR that changes acquisition order
    shows the diff in review;
  * renders GraphViz DOT (``--dot [PATH]``, '-' for stdout) for the
    humans;
  * gates CI (``--check``): exit 2 when the CURRENT graph has a cycle
    (a potential deadlock -- never committable), exit 1 when the
    current graph drifts from the committed LOCK_ORDER.json (run
    ``--update`` and review the diff), exit 0 when clean. The shared
    lint exit contract, joined to scripts/lint_all.sh.

The runtime complement is the lock-order witness (utils/locks.py):
same node identities, enforced at acquire time under chaos and the
armed tier-1 cluster test.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from presto_tpu.lint.core import REPO, get_pass  # noqa: E402
from presto_tpu.lint.passes.lock_order import (  # noqa: E402
    program_for_targets)

DEFAULT_ARTIFACT = os.path.join(REPO, "LOCK_ORDER.json")


def build_doc() -> dict:
    targets = get_pass("C002").target_files()
    return program_for_targets(targets).to_doc()


def render_dot(doc: dict) -> str:
    """GraphViz digraph: one node per lock (colored by module), one
    edge per established order, cycles (if any) in red."""
    cyc_edges = set()
    for cyc in doc.get("cycles", []):
        ring = cyc + [cyc[0]]
        cyc_edges.update(zip(ring, ring[1:]))
    lines = ["digraph lock_order {",
             '  rankdir=LR; node [shape=box, fontsize=10];']
    mods = {}
    for n in doc["nodes"]:
        mod = n["id"].split(".")[0]
        mods.setdefault(mod, []).append(n)
    used = {e["from"] for e in doc["edges"]} | \
           {e["to"] for e in doc["edges"]}
    for mod, nodes in sorted(mods.items()):
        shown = [n for n in nodes if n["id"] in used]
        if not shown:
            continue
        lines.append(f'  subgraph "cluster_{mod}" {{ label="{mod}";')
        for n in shown:
            lines.append(f'    "{n["id"]}" [label="{n["id"]}"];')
        lines.append("  }")
    for e in doc["edges"]:
        attrs = [f'label="{os.path.basename(e["file"])}:{e["line"]}"',
                 "fontsize=8"]
        if (e["from"], e["to"]) in cyc_edges:
            attrs.append("color=red penwidth=2")
        lines.append(f'  "{e["from"]}" -> "{e["to"]}" '
                     f'[{" ".join(attrs)}];')
    lines.append("}")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="lockgraph",
        description="server-tier lock-order graph: artifact, DOT, gate")
    ap.add_argument("--artifact", default=DEFAULT_ARTIFACT,
                    help=f"graph artifact path (default "
                         f"{os.path.relpath(DEFAULT_ARTIFACT, REPO)})")
    ap.add_argument("--update", action="store_true",
                    help="rewrite the committed artifact from source")
    ap.add_argument("--check", action="store_true",
                    help="gate: exit 2 on cycle, 1 on drift vs the "
                         "committed artifact, 0 clean")
    ap.add_argument("--dot", nargs="?", const="-", metavar="PATH",
                    help="render GraphViz DOT to PATH ('-' = stdout)")
    args = ap.parse_args(argv)

    try:
        doc = build_doc()
    except (OSError, SyntaxError) as e:
        print(f"lockgraph: {type(e).__name__}: {e}", file=sys.stderr)
        return 2

    if args.dot is not None:
        dot = render_dot(doc)
        if args.dot == "-":
            sys.stdout.write(dot)
        else:
            with open(args.dot, "w", encoding="utf-8") as f:
                f.write(dot)
            print(f"lockgraph: wrote {args.dot}")

    if args.update:
        with open(args.artifact, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"lockgraph: wrote {args.artifact} "
              f"({len(doc['nodes'])} locks, {len(doc['edges'])} edges, "
              f"{len(doc['cycles'])} cycles)")

    if args.check:
        if doc["cycles"]:
            for cyc in doc["cycles"]:
                print(f"lockgraph: CYCLE {' -> '.join(cyc + [cyc[0]])}",
                      file=sys.stderr)
            return 2
        try:
            with open(args.artifact, encoding="utf-8") as f:
                committed = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"lockgraph: cannot read committed artifact: {e} "
                  f"-- run scripts/lockgraph.py --update",
                  file=sys.stderr)
            return 1
        # STRUCTURAL drift only (lock set + edge set): evidence line
        # numbers move on every unrelated edit and must not fail CI
        cn = {n["id"] for n in committed.get("nodes", [])}
        dn = {n["id"] for n in doc["nodes"]}
        ce = {(e["from"], e["to"]) for e in committed.get("edges", [])}
        de = {(e["from"], e["to"]) for e in doc["edges"]}
        if cn != dn or ce != de:
            for x in sorted(dn - cn):
                print(f"lockgraph: new lock {x}", file=sys.stderr)
            for x in sorted(cn - dn):
                print(f"lockgraph: removed lock {x}", file=sys.stderr)
            for a, b in sorted(de - ce):
                print(f"lockgraph: new edge {a} -> {b}", file=sys.stderr)
            for a, b in sorted(ce - de):
                print(f"lockgraph: removed edge {a} -> {b}",
                      file=sys.stderr)
            print("lockgraph: drift vs committed artifact -- run "
                  "scripts/lockgraph.py --update and review the diff",
                  file=sys.stderr)
            return 1
        print(f"lockgraph: ok ({len(doc['nodes'])} locks, "
              f"{len(doc['edges'])} edges, cycle-free, matches "
              f"{os.path.relpath(args.artifact, os.getcwd())})")
        return 0

    if not (args.update or args.dot):
        # default: print the doc (machine-readable, like --json tools)
        json.dump(doc, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
