#!/usr/bin/env python
"""Micro-benchmark the pipeline-region fusion executor (exec/regions.py).

The oracle-checked A/B grid the fusion PR gates on:

    (fused | per-op materialized) x (narrow on | off) x (q1 | q6 chains)

Each cell runs the REAL front door (SQL -> prepare_plan -> region
partition -> region executor) at MB_SF, times end-to-end wall over
MB_ITERS repeats (plan cache warm after the first), and reads the
engine's own QueryStats for the execute-stage split and the region
count -- so the grid measures exactly what ships, not a lab kernel.
Every cell's rows are asserted equal to the fused-narrow baseline
cell's (bit-exact fusion law, the same invariant
tests/test_fusion_regions.py pins across TPC-H q1-q22).

Env knobs: MB_SF (default 0.05), MB_ITERS (default 3).
``--json`` emits one machine-readable line (PERF.md / BENCH artifact
paste material).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import presto_tpu  # noqa: F401  (x64 on, before any array is created)

SF = float(os.environ.get("MB_SF", "0.05"))
ITERS = int(os.environ.get("MB_ITERS", "3"))

# q1: the scan->filter->project->agg->sort fusion flagship; q6: the
# pure filter->project->global-agg chain (no group table at all)
QUERIES = (1, 6)


def canon(res):
    # QueryResult.canonical_rows: the shared oracle canonicalization
    return res.canonical_rows(digits=3)


def run_cell(qnum, narrow, fused):
    """One grid cell: ITERS timed runs; returns (canon rows, metrics)."""
    from presto_tpu.queries.tpch_sql import tpch_query
    from presto_tpu.sql import sql as run_sql

    q = tpch_query(qnum)
    os.environ["PRESTO_TPU_NARROW"] = "1" if narrow else "0"
    try:
        session = {"fusion": bool(fused)}
        walls, res = [], None
        kw = dict(max_groups=q.max_groups)
        if q.join_capacity:
            kw["join_capacity"] = q.join_capacity
        cold0 = time.time()
        res = run_sql(q.text, sf=SF, session=session, **kw)
        cold_s = time.time() - cold0
        for _ in range(ITERS):
            t0 = time.time()
            res = run_sql(q.text, sf=SF, session=session, **kw)
            walls.append(time.time() - t0)
        qs = res.query_stats
        regions = int((res.stats.get("fusion_regions") or {}).get("max", 1))
        metrics = {
            "query": f"q{qnum}",
            "fusion": "fused" if fused else "per-op",
            "narrow": bool(narrow),
            "cold_wall_s": round(cold_s, 4),
            "warm_wall_s": round(float(np.median(walls)), 4),
            "execute_s": round(qs.stage_us("execute") / 1e6, 4),
            "staging_s": round(qs.stage_us("staging") / 1e6, 4),
            "regions": regions,
        }
        return canon(res), metrics
    finally:
        os.environ.pop("PRESTO_TPU_NARROW", None)


def main() -> int:
    import jax
    platform = jax.devices()[0].platform
    rows = []
    oracles = {}
    for qnum in QUERIES:
        for narrow in (True, False):
            for fused in (True, False):
                got, metrics = run_cell(qnum, narrow, fused)
                if qnum not in oracles:
                    oracles[qnum] = got
                elif got != oracles[qnum]:
                    print(f"ORACLE MISMATCH: q{qnum} "
                          f"fusion={metrics['fusion']} "
                          f"narrow={narrow}", file=sys.stderr)
                    return 1
                rows.append(metrics)
    doc = {"platform": platform, "sf": SF, "iters": ITERS,
           "oracle": "all cells bit-equal per query", "cells": rows}
    if "--json" in sys.argv:
        print(json.dumps(doc, sort_keys=True))
        return 0
    print(f"platform={platform} sf={SF} iters={ITERS} "
          f"(fused-vs-materialized A/B; oracle-checked)")
    print(f"{'cell':34s} {'cold':>8s} {'warm':>8s} {'execute':>9s} "
          f"{'staging':>9s} {'regions':>8s}")
    for m in rows:
        name = (f"{m['query']} {m['fusion']}"
                f"{' narrow' if m['narrow'] else ' wide'}")
        print(f"{name:34s} {m['cold_wall_s']:7.3f}s {m['warm_wall_s']:7.3f}s "
              f"{m['execute_s']:8.4f}s {m['staging_s']:8.4f}s "
              f"{m['regions']:8d}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
