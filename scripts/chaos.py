#!/usr/bin/env python
"""Seeded chaos soak: the TPC-H corpus under generated fault schedules.

Drives an in-process multi-worker cluster (workers + coordinator +
statement tier + discovery + prober -- the DistributedQueryRunner
harness pattern) through a DETERMINISTIC schedule of fault injections
(presto_tpu/failpoints), armed round by round over the live admin API
(``POST /v1/failpoint``), and asserts the four soak invariants:

  1. correct-or-clean-failure: every chaos query either matches its
     fault-free oracle result or raises a clean error within its
     deadline;
  2. no hangs: a watchdog bounds every query; no metrics counter
     decreases across the run (monotonicity audited per round from
     real ``/v1/metrics`` scrapes);
  3. full fault accounting: every fired injection shows up in the
     ``presto_tpu_failpoint_hits_total{site,action}`` counters AND as
     a flight-recorder ``failpoint`` event (and a statement-tier
     failure round checks its auto flight DUMP carries them);
  4. lock-order consistency: the runtime witness (utils/locks.py) is
     ARMED for the whole soak -- every OrderedLock acquire on every
     tier is checked against the process's established acquisition
     order, and a single inversion anywhere fails its round.

Determinism contract: with a fixed ``--seed``, two runs produce an
identical fault sequence and identical per-query outcomes -- the
report's ``determinism`` section hashes to the same digest. Schedules
therefore use ``once``-triggered faults (fire counts are invariant to
poll timing); ``prob``/``every`` trigger determinism is pinned by
tests/test_failpoints.py at the registry level.

  python scripts/chaos.py --seed 42 --smoke            # pre-PR gate
  python scripts/chaos.py --seed 7 --queries 1,3,6 --schedule 12
  python scripts/chaos.py --seed 42 --report /tmp/chaos.json

Exit codes: 0 invariants hold, 1 invariant violated, 2 harness error.
"""

import argparse
import hashlib
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

# repo root importable + the shared CPU-forcing armor
sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _cpu  # noqa: E402,F401

from presto_tpu import failpoints  # noqa: E402
from presto_tpu.utils import locks as wlocks  # noqa: E402
from presto_tpu.client import StatementClient, QueryError  # noqa: E402
from presto_tpu.exec import run_query  # noqa: E402
from presto_tpu.plan.distribute import add_exchanges  # noqa: E402
from presto_tpu.queries.tpch_sql import tpch_query  # noqa: E402
from presto_tpu.server import Coordinator, TpuWorkerServer  # noqa: E402
from presto_tpu.server.discovery import (Announcer,  # noqa: E402
                                         DiscoveryServer, HeartbeatProber,
                                         alive_nodes)
from presto_tpu.server.flight_recorder import (FlightRecorder,  # noqa: E402
                                               get_flight_recorder,
                                               set_flight_recorder)
from presto_tpu.server.metrics import parse_prometheus  # noqa: E402
from presto_tpu.server.statement import StatementServer  # noqa: E402
from presto_tpu.sql import plan_sql  # noqa: E402

SMOKE_QUERIES = (1, 6)
FULL_QUERIES = (1, 3, 4, 6, 12, 14, 19)

# The fault palette: (layer, site, spec). All `once`-triggered --
# deterministic fire counts regardless of poll timing -- and all
# verified to leave a recoverable or cleanly-failing cluster. The
# schedule's coverage prefix walks every entry once (so each smoke run
# fires >= 5 distinct sites across exchange/serde/task/memory/
# discovery); extra rounds draw from QUERY_FAULTS with the seeded RNG.
QUERY_FAULTS = [
    ("exchange", "exchange.fetch", "error(ConnectionError):once"),
    ("exchange", "exchange.serve", "drop_conn:once"),
    ("serde", "serde.deserialize", "corrupt_page:once"),
    ("serde", "serde.serialize", "error(ValueError):once"),
    ("task", "worker.run_task", "error(RuntimeError):once"),
    ("task", "task.submit", "error(ConnectionError):once"),
    ("task", "task.status", "error(ConnectionError):once"),
    ("task", "task.result", "error(ConnectionError):once"),
    ("task", "client.request", "drop_conn:once"),
    ("task", "worker.run_task", "delay(250):once"),
    ("memory", "memory.reserve", "oom:once"),
]
# non-query rounds: discovery ops + statement-tier rounds (dispatcher
# stall, failed-query flight dump, hang vs client poll deadline)
OP_ROUNDS = [
    ("discovery", "announce"),
    ("discovery", "probe"),
    ("dispatcher", "admit"),
    ("dispatcher", "batch"),
    ("statement", "fail_dump"),
    ("statement", "hang_deadline"),
    ("task", "stuck"),
    ("fusion", "demote"),
    ("fusion", "donation"),
    ("timeline", "timeline_degrade"),
    ("fleet", "elastic"),
    ("fleet", "speculate"),
]


def canon_rows(cols):
    """Coordinator/local result columns -> canonical sorted row tuples
    (floats rounded so distributed summation order cannot flip a
    match verdict)."""
    rows = []
    n = len(cols[0][0]) if cols else 0
    for i in range(n):
        row = []
        for v, nl in cols:
            if bool(nl[i]):
                row.append(None)
                continue
            x = v[i].item() if hasattr(v[i], "item") else v[i]
            if isinstance(x, float):
                x = round(x, 3)
            row.append(x)
        rows.append(tuple(row))
    return sorted(rows, key=lambda r: tuple((x is None, str(x))
                                            for x in r))


class Watchdog:
    """Run fn() on a thread, bounded by a deadline: the no-hangs
    invariant's enforcement. -> ("ok", value) | ("error", exc) |
    ("hung", None)."""

    def __init__(self, fn, deadline_s: float):
        self.fn = fn
        self.deadline_s = deadline_s
        self.value = None
        self.error = None
        self.done = False

    def run(self):
        def target():
            try:
                self.value = self.fn()
            except BaseException as e:  # noqa: BLE001 - verdict data
                self.error = e
            self.done = True
        t = threading.Thread(target=target, daemon=True)
        t.start()
        t.join(self.deadline_s)
        if not self.done:
            return "hung", None
        if self.error is not None:
            return "error", self.error
        return "ok", self.value


class ChaosCluster:
    """In-process cluster: N workers + coordinator (explicit URLs for
    query traffic), a statement tier, and a discovery server whose
    announcer/prober the driver steps MANUALLY -- discovery faults
    then fire a deterministic number of times."""

    def __init__(self, sf: float, workers: int = 2):
        self.sf = sf
        self.workers = [TpuWorkerServer(sf=sf).start()
                        for _ in range(workers)]
        self.urls = [f"http://127.0.0.1:{w.port}" for w in self.workers]
        self.coordinator = Coordinator(self.urls)
        self.statement = StatementServer(sf=sf).start()
        self.discovery = DiscoveryServer().start()
        # driver-stepped: start() is never called on this announcer
        self.announcer = Announcer(self.discovery.url, "chaos-node",
                                   self.urls[0], interval_s=3600.0)
        self.prober = HeartbeatProber(lambda: self.urls, decay=0.0)

    def stop(self):
        for w in self.workers:
            try:
                w.stop()
            except Exception:  # noqa: BLE001 - already stopped
                pass
        self.statement.stop()
        self.discovery.stop()

    # -- admin API (the live-flip path under test) ---------------------

    def _admin(self, method: str, path: str, body=None) -> dict:
        import urllib.request
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            f"{self.urls[0]}{path}", data=data, method=method,
            headers={"Content-Type": "application/json"} if data else {})
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    def arm(self, site: str, spec: str) -> None:
        doc = self._admin("POST", "/v1/failpoint",
                          {"site": site, "spec": spec})
        assert site in doc.get("active", {}), doc

    def armed_doc(self) -> dict:
        return self._admin("GET", "/v1/failpoint")

    def disarm_all(self) -> None:
        self._admin("DELETE", "/v1/failpoint")

    # -- metrics -------------------------------------------------------

    def scrapes(self) -> dict:
        """{endpoint: parsed /v1/metrics} over every HTTP tier."""
        import urllib.request
        out = {}
        for name, base in [("worker0", self.urls[0]),
                           ("statement", self.statement.url)]:
            with urllib.request.urlopen(f"{base}/v1/metrics",
                                        timeout=10) as r:
                out[name] = parse_prometheus(r.read().decode())
        return out


def monotonicity_violations(before: dict, after: dict) -> list:
    """Counter samples (plain *_total + histogram _bucket/_count/_sum)
    that DECREASED between two parsed scrapes of one endpoint."""
    bad = []
    for fam, samples in after.items():
        if not (fam.endswith(("_total", "_bucket", "_count", "_sum"))):
            continue
        for key, val in samples.items():
            prev = before.get(fam, {}).get(key)
            if prev is not None and val < prev - 1e-9:
                bad.append(f"{fam}{key}: {prev} -> {val}")
    return bad


def failpoint_counter_totals(parsed: dict) -> dict:
    """{(site, action): value} from a parsed scrape."""
    import re
    out = {}
    for key, val in parsed.get("presto_tpu_failpoint_hits_total",
                               {}).items():
        site = re.search(r'site="([^"]+)"', key)
        action = re.search(r'action="([^"]+)"', key)
        if site and action and site.group(1) != "none":
            out[(site.group(1), action.group(1))] = val
    return out


def build_schedule(seed: int, queries, rounds: int):
    """The deterministic round list: a coverage prefix (every palette
    entry + every op round once) then seeded extra draws up to
    `rounds`. Queries rotate deterministically; the RNG never touches
    the prefix, so coverage is identical at every seed."""
    import random
    rng = random.Random(seed)
    sched = []
    qcycle = list(queries)
    for i, (layer, site, spec) in enumerate(QUERY_FAULTS):
        sched.append({"kind": "query", "query": qcycle[i % len(qcycle)],
                      "layer": layer, "site": site, "spec": spec})
    for layer, op in OP_ROUNDS:
        sched.append({"kind": "op", "op": op, "layer": layer})
    while len(sched) < rounds:
        layer, site, spec = rng.choice(QUERY_FAULTS)
        sched.append({"kind": "query", "query": rng.choice(qcycle),
                      "layer": layer, "site": site, "spec": spec})
    return sched


def ring_fires_since(t0_us: int, site: str) -> int:
    """Flight-recorder `failpoint` events for `site` recorded at or
    after t0_us -- the per-round fault/flight accounting source."""
    return sum(1 for e in get_flight_recorder().events(kind="failpoint")
               if e.get("site") == site and e["tsUs"] >= t0_us)


class ChaosRun:
    def __init__(self, args):
        self.args = args
        self.sf = args.sf
        self.failures: list = []       # invariant violations (exit 1)
        self.rounds: list = []         # determinism section rows
        self.expected_fires: dict = {}  # (site, action) -> total fires
        self.oracles: dict = {}
        self.plans: dict = {}

    def fail(self, message: str):
        print(f"INVARIANT VIOLATION: {message}", file=sys.stderr)
        self.failures.append(message)

    # -- per-round drivers ---------------------------------------------

    def warm(self, cluster: ChaosCluster, queries):
        """Fault-free oracles (and warm plan/fragment caches, so round
        timings -- and cache-dependent fire locations -- are identical
        between same-seed runs)."""
        for n in queries:
            q = tpch_query(n)
            plan = plan_sql(q.text, max_groups=q.max_groups,
                            join_capacity=q.join_capacity)
            local = run_query(plan, sf=self.sf,
                              default_join_capacity=q.join_capacity
                              or 1 << 16)
            cols = [(np.asarray(local.columns[c]),
                     np.asarray(local.nulls[c]))
                    for c in range(len(local.columns))]
            self.oracles[n] = canon_rows(cols)
            self.plans[n] = add_exchanges(plan_sql(
                q.text, max_groups=q.max_groups,
                join_capacity=q.join_capacity))
            got, _ = cluster.coordinator.execute(
                self.plans[n], sf=self.sf, timeout=self.args.timeout)
            if canon_rows(got) != self.oracles[n]:
                raise RuntimeError(
                    f"fault-free distributed q{n} does not match its "
                    f"local oracle -- engine bug, not chaos")

    def query_round(self, cluster: ChaosCluster, step: dict) -> str:
        n = step["query"]
        def go():
            cols, _ = cluster.coordinator.execute(
                self.plans[n], sf=self.sf, timeout=self.args.timeout)
            return canon_rows(cols)
        status, value = Watchdog(go, self.args.timeout + 30).run()
        if status == "hung":
            self.fail(f"q{n} under {step['site']}={step['spec']} HUNG "
                      f"past {self.args.timeout + 30}s")
            return "HUNG"
        if status == "error":
            return f"clean_failure:{type(value).__name__}"
        if value != self.oracles[n]:
            self.fail(f"q{n} under {step['site']}={step['spec']} "
                      f"returned WRONG rows")
            return "WRONG_RESULT"
        return "match"

    def op_round(self, cluster: ChaosCluster, step: dict) -> str:
        op = step["op"]
        if op == "announce":
            step["site"], step["spec"] = \
                "discovery.announce", "error(OSError):once"
            cluster.arm(step["site"], step["spec"])
            try:
                cluster.announcer.announce_once()
                return "UNFIRED"  # the once-error must have raised
            except OSError:
                pass
            cluster.announcer.announce_once()  # recovery announcement
            nodes = alive_nodes(cluster.discovery.url)
            return "recovered" if any(
                x["nodeId"] == "chaos-node" for x in nodes) \
                else "NOT_RECOVERED"
        if op == "probe":
            step["site"], step["spec"] = \
                "discovery.probe", "error(OSError):once"
            cluster.arm(step["site"], step["spec"])
            cluster.prober.probe_all_once()   # one probe eats the fault
            cluster.prober.probe_all_once()   # decay=0: full recovery
            healthy = sorted(cluster.prober.healthy())
            return "recovered" if healthy == sorted(
                u.rstrip("/") for u in cluster.urls) else "NOT_RECOVERED"
        if op == "admit":
            step["site"], step["spec"] = \
                "dispatcher.admit", "delay(100):once"
            cluster.arm(step["site"], step["spec"])
            c = StatementClient(cluster.statement.url,
                                "SELECT 1", deadline_s=60).drain()
            return "match" if c.data == [[1]] else "WRONG_RESULT"
        if op == "batch":
            # a FORMED query batch forced to collapse back to serial
            # dispatch mid-flight (PR 13): co-batchable point lookups
            # form one batch under a long window, the
            # dispatcher.batch_collapse failpoint fires before the
            # vmapped dispatch, and every member must still match its
            # serial oracle while the collapse is fully accounted
            # (reason counter + flight event + the generic fires/ring
            # legs the driver audits for every round)
            from presto_tpu.exec.batching import (batching_totals,
                                                  get_batching_executor)
            from presto_tpu.sql import sql as engine_sql
            step["site"], step["spec"] = \
                "dispatcher.batch_collapse", "error(RuntimeError):once"
            texts = ["SELECT custkey, name, acctbal FROM customer "
                     f"WHERE custkey = {k}" for k in (7, 11, 23, 42)]
            oracles = []
            for t in texts:
                r = engine_sql(t, sf=self.sf,
                               session={"query_batching": "false"})
                oracles.append(canon_rows(
                    [(np.asarray(r.columns[c]), np.asarray(r.nulls[c]))
                     for c in range(len(r.columns))]))
            before = batching_totals()["collapses"].get("failpoint", 0)
            cluster.arm(step["site"], step["spec"])
            sess = {"query_batching": "true", "batch_window_ms": "500",
                    "batch_hot_min": "1"}
            executor = get_batching_executor()
            results = [None] * len(texts)
            errors = [None] * len(texts)

            def member(i, t):
                try:
                    res = executor.try_execute(
                        t, sf=self.sf, session=sess,
                        query_id=f"chaos-batch-{i}")
                    if res is None:  # no batch formed for this member
                        res = engine_sql(t, sf=self.sf, session=sess)
                    results[i] = res
                except BaseException as e:  # noqa: BLE001 - verdict
                    errors[i] = e

            threads = [threading.Thread(target=member, args=(i, t),
                                        daemon=True)
                       for i, t in enumerate(texts)]
            threads[0].start()      # the leader opens the window ...
            time.sleep(0.1)
            for t in threads[1:]:   # ... followers join inside it
                t.start()
            for t in threads:
                t.join(60)
            if any(not r and e is None
                   for r, e in zip(results, errors)):
                self.fail("batch round: a member HUNG past 60s")
                return "HUNG"
            for i, e in enumerate(errors):
                if e is not None:
                    self.fail(f"batch round: member {i} failed under "
                              f"collapse: {type(e).__name__}: {e}")
                    return f"clean_failure:{type(e).__name__}"
            for i, r in enumerate(results):
                got = canon_rows(
                    [(np.asarray(r.columns[c]), np.asarray(r.nulls[c]))
                     for c in range(len(r.columns))])
                if got != oracles[i]:
                    self.fail(f"batch round: member {i} under forced "
                              f"collapse returned WRONG rows")
                    return "WRONG_RESULT"
            delta = batching_totals()["collapses"].get("failpoint", 0) \
                - before
            if delta != 1:
                self.fail(f"batch round: collapse counter moved {delta} "
                          f"(expected exactly 1 collapsed batch)")
                return "UNACCOUNTED_COLLAPSE"
            if not get_flight_recorder().events(kind="batch_collapse"):
                self.fail("batch round: collapse without a "
                          "batch_collapse flight event")
                return "NO_FLIGHT_EVENT"
            return "match+collapsed"
        if op == "fail_dump":
            step["site"], step["spec"] = \
                "statement.execute", "error(RuntimeError):once"
            cluster.arm(step["site"], step["spec"])
            qid = None
            try:
                c = StatementClient(cluster.statement.url,
                                    "SELECT 2", deadline_s=60)
                qid = c.query_id
                c.drain()
                return "UNFIRED"
            except QueryError:
                pass
            # the failed query must auto-dump, and the dump must carry
            # the failpoint event (full fault accounting, dump leg)
            deadline = time.time() + 5
            path = None
            while path is None and time.time() < deadline:
                path = get_flight_recorder().dump_path(qid) \
                    if qid else None
                if path is None:
                    time.sleep(0.05)
            if path is None:
                self.fail("failed statement produced no flight dump")
                return "NO_DUMP"
            with open(path) as f:
                dumped = [json.loads(line) for line in f]
            if not any(e.get("kind") == "failpoint" and
                       e.get("site") == "statement.execute"
                       for e in dumped):
                self.fail(f"flight dump {path} missing the injected "
                          f"failpoint event")
                return "DUMP_MISSING_FAULT"
            return "clean_failure:dumped"
        if op == "stuck":
            # the hang failpoint's DETERMINISTIC detector (PR 10): a
            # bounded worker hang well past the stuck threshold must
            # fire the stuck-progress watchdog -- counter bump +
            # flight-recorder stuck_progress event -- while the query
            # still completes and matches its oracle afterwards
            from presto_tpu.server.watchdog import stuck_totals
            step["site"], step["spec"] = \
                "worker.run_task", "hang(1200):once"
            n = min(self.oracles)  # deterministic query choice
            before = stuck_totals()
            cluster.arm(step["site"], step["spec"])
            os.environ["PRESTO_TPU_STUCK_MS"] = "300"
            try:
                def go():
                    cols, _ = cluster.coordinator.execute(
                        self.plans[n], sf=self.sf,
                        timeout=self.args.timeout)
                    return canon_rows(cols)
                status, value = Watchdog(go, self.args.timeout + 30).run()
            finally:
                os.environ.pop("PRESTO_TPU_STUCK_MS", None)
            if status == "hung":
                self.fail(f"stuck round: q{n} HUNG past the deadline")
                return "HUNG"
            if status == "error":
                return f"clean_failure:{type(value).__name__}"
            if value != self.oracles[n]:
                self.fail(f"stuck round: q{n} returned WRONG rows")
                return "WRONG_RESULT"
            if stuck_totals() <= before:
                self.fail("stuck round: the hang fired but the "
                          "stuck-progress watchdog never did")
                return "UNDETECTED"
            if not get_flight_recorder().events(kind="stuck_progress"):
                self.fail("stuck round: watchdog fired without a "
                          "stuck_progress flight event")
                return "NO_FLIGHT_EVENT"
            return "match+stuck_detected"
        if op == "demote":
            # forced mid-query fusion demotion (PR 11): the
            # fusion.demote failpoint demotes the first fused multi-op
            # span a worker dispatches; that query must STILL match its
            # oracle (the materialized region executor is bit-identical
            # to the fused program), the demotion must land as a
            # fusion_demotion flight event, and the round clears the
            # sticky demotion afterwards so later rounds run fused
            from presto_tpu.exec.regions import fusion_memory
            step["site"], step["spec"] = "fusion.demote", "error:once"
            n = min(self.oracles)  # deterministic query choice
            cluster.arm(step["site"], step["spec"])
            try:
                def go():
                    cols, _ = cluster.coordinator.execute(
                        self.plans[n], sf=self.sf,
                        timeout=self.args.timeout)
                    return canon_rows(cols)
                status, value = Watchdog(go, self.args.timeout + 30).run()
            finally:
                demoted = fusion_memory().snapshot()["demoted"]
                fusion_memory().clear()
            if status == "hung":
                self.fail(f"fusion round: q{n} HUNG past the deadline")
                return "HUNG"
            if status == "error":
                return f"clean_failure:{type(value).__name__}"
            if value != self.oracles[n]:
                self.fail(f"fusion round: q{n} under forced demotion "
                          f"returned WRONG rows")
                return "WRONG_RESULT"
            if not demoted:
                self.fail("fusion round: the demote failpoint fired "
                          "but no span was demoted")
                return "NOT_DEMOTED"
            if not get_flight_recorder().events(kind="fusion_demotion"):
                self.fail("fusion round: demotion without a "
                          "fusion_demotion flight event")
                return "NO_FLIGHT_EVENT"
            return "match+demoted"
        if op == "donation":
            # forced donation-path failure (this PR): with buffer
            # donation on under the materialized region executor, the
            # donation.apply failpoint kills the prepare step for the
            # first donation-eligible region BEFORE any buffer is
            # consumed -- the dispatch must collapse to the undonated
            # form with rows still matching the donation-off oracle,
            # the fallback counted presto_tpu_donation_fallbacks_total,
            # and a donation_fallback flight event on the timeline
            from presto_tpu.exec.donation import donation_totals
            from presto_tpu.queries.tpch_sql import tpch_query
            from presto_tpu.sql import sql as engine_sql
            step["site"], step["spec"] = "donation.apply", "error:once"
            q = tpch_query(6)
            oracle = engine_sql(q.text, sf=self.sf,
                                session={"fusion": False},
                                max_groups=q.max_groups)
            before = donation_totals()["fallbacks"]
            cluster.arm(step["site"], step["spec"])
            sess = {"fusion": False, "buffer_donation": True}
            try:
                res = engine_sql(q.text, sf=self.sf, session=sess,
                                 max_groups=q.max_groups)
            except BaseException as e:  # noqa: BLE001 - verdict
                self.fail(f"donation round: query FAILED under forced "
                          f"fallback: {type(e).__name__}: {e}")
                return f"clean_failure:{type(e).__name__}"
            if res.canonical_rows() != oracle.canonical_rows():
                self.fail("donation round: forced fallback returned "
                          "WRONG rows")
                return "WRONG_RESULT"
            if donation_totals()["fallbacks"] - before < 1:
                self.fail("donation round: the failpoint fired but no "
                          "fallback was counted")
                return "UNACCOUNTED_FALLBACK"
            if not get_flight_recorder().events(
                    kind="donation_fallback"):
                self.fail("donation round: fallback without a "
                          "donation_fallback flight event")
                return "NO_FLIGHT_EVENT"
            return "match+fallback"
        if op == "timeline_degrade":
            # forced interval-ledger failure (this PR): with timeline
            # recording on (the default), the timeline.record failpoint
            # kills the first interval append -- the ledger must
            # degrade STICKY to counted totals (intervals drop, hop
            # totals keep folding), the query must still match its
            # fault-free oracle, the degradation must be counted in the
            # process registry, and a timeline_degraded flight event
            # must land on the query's timeline
            from presto_tpu.exec.timeline import timeline_totals
            from presto_tpu.queries.tpch_sql import tpch_query
            from presto_tpu.sql import sql as engine_sql
            step["site"], step["spec"] = "timeline.record", "error:once"
            q = tpch_query(6)
            oracle = engine_sql(q.text, sf=self.sf,
                                session={"timeline": False},
                                max_groups=q.max_groups)
            before = timeline_totals()["degraded"]
            cluster.arm(step["site"], step["spec"])
            try:
                res = engine_sql(q.text, sf=self.sf,
                                 max_groups=q.max_groups)
            except BaseException as e:  # noqa: BLE001 - verdict
                self.fail(f"timeline round: query FAILED under forced "
                          f"ledger degradation: {type(e).__name__}: {e}")
                return f"clean_failure:{type(e).__name__}"
            if res.canonical_rows() != oracle.canonical_rows():
                self.fail("timeline round: forced degradation returned "
                          "WRONG rows")
                return "WRONG_RESULT"
            if timeline_totals()["degraded"] - before < 1:
                self.fail("timeline round: the failpoint fired but no "
                          "degradation was counted")
                return "UNACCOUNTED_DEGRADATION"
            qs = res.query_stats
            if qs.timeline.intervals or not qs.timeline.totals:
                self.fail("timeline round: degraded ledger must keep "
                          "counted totals and drop intervals")
                return "NOT_DEGRADED_TO_TOTALS"
            if not get_flight_recorder().events(
                    kind="timeline_degraded"):
                self.fail("timeline round: degradation without a "
                          "timeline_degraded flight event")
                return "NO_FLIGHT_EVENT"
            return "match+degraded"
        if op == "elastic":
            # the elastic-fleet acceptance round: an 8-worker
            # discovery-backed cluster changes shape MID-QUERY -- kill
            # 2 workers, add 2, gracefully drain 1 (pages migrating to
            # a peer) -- and the query must still match its fault-free
            # oracle, the drained worker must end DRAINED with ZERO
            # unreplayed buffered pages, and the armed drain_stall
            # fault must be fully accounted like every other round
            from presto_tpu.server.client import WorkerClient
            step["site"], step["spec"] = \
                "worker.drain_stall", "delay(100):once"
            n = min(self.oracles)  # deterministic query choice
            cluster.arm(step["site"], step["spec"])
            disc = DiscoveryServer().start()
            fleet = [TpuWorkerServer(sf=self.sf, discovery_url=disc.url,
                                     announce_interval_s=0.2).start()
                     for _ in range(8)]
            try:
                deadline = time.time() + 10
                while time.time() < deadline and \
                        len(alive_nodes(disc.url)) < 8:
                    time.sleep(0.05)
                coord = Coordinator(discovery_url=disc.url)
                drained_w, peer_w = fleet[2], fleet[3]

                def churn():
                    time.sleep(0.15)
                    fleet[0].kill()                       # kill 2
                    fleet[1].kill()  # (ungraceful: no unannounce)
                    for _ in range(2):                    # add 2
                        fleet.append(TpuWorkerServer(
                            sf=self.sf, discovery_url=disc.url,
                            announce_interval_s=0.2).start())
                    WorkerClient(                         # drain 1
                        f"http://127.0.0.1:{drained_w.port}", 10).drain(
                        migrate_to=f"http://127.0.0.1:{peer_w.port}",
                        timeout_ms=20000)
                churner = threading.Thread(target=churn, daemon=True)

                def go():
                    churner.start()
                    cols, _ = coord.execute(self.plans[n], sf=self.sf,
                                            timeout=self.args.timeout)
                    return canon_rows(cols)
                status, value = Watchdog(go, self.args.timeout + 30).run()
                churner.join(30)
                if status == "hung":
                    self.fail(f"elastic round: q{n} HUNG past deadline")
                    return "HUNG"
                if status == "error":
                    self.fail(f"elastic round: q{n} failed under fleet "
                              f"churn: {type(value).__name__}: {value}")
                    return f"clean_failure:{type(value).__name__}"
                if value != self.oracles[n]:
                    self.fail(f"elastic round: q{n} under kill/add/"
                              f"drain returned WRONG rows")
                    return "WRONG_RESULT"
                # the drained worker must settle DRAINED with zero
                # unreplayed pages (the graceful-exit acceptance bar)
                deadline = time.time() + 25
                st = drained_w.drain_status()
                while time.time() < deadline and \
                        st["state"] != "DRAINED":
                    time.sleep(0.1)
                    st = drained_w.drain_status()
                if st["state"] != "DRAINED" or \
                        st["unreplayedPages"] != 0:
                    self.fail(f"elastic round: drained worker ended "
                              f"{st}")
                    return "UNREPLAYED_PAGES"
                return "match+drained"
            finally:
                for w in fleet:
                    try:
                        w.stop()
                    except Exception:  # noqa: BLE001 - already stopped
                        pass
                disc.stop()
        if op == "speculate":
            # straggler rescue: ONE task hangs well past the
            # speculation threshold; the coordinator must re-run it
            # elsewhere, the speculative copy must WIN (counter > 0),
            # and the result must match the oracle -- speculation never
            # duplicates or drops rows (first-result-wins dedup)
            from presto_tpu.server.coordinator import speculation_totals
            step["site"], step["spec"] = \
                "worker.run_task", "hang(1800):once"
            n = min(self.oracles)  # deterministic query choice
            before = speculation_totals()["wins"]
            cluster.arm(step["site"], step["spec"])
            spec_coord = Coordinator(cluster.urls,
                                     speculation_threshold_ms=300)

            def go():
                cols, _ = spec_coord.execute(self.plans[n], sf=self.sf,
                                             timeout=self.args.timeout)
                return canon_rows(cols)
            status, value = Watchdog(go, self.args.timeout + 30).run()
            if status == "hung":
                self.fail(f"speculate round: q{n} HUNG past deadline")
                return "HUNG"
            if status == "error":
                # this round's whole point is that speculation RESCUES
                # the straggler -- a clean failure means it did not
                self.fail(f"speculate round: q{n} failed instead of "
                          f"being rescued: {type(value).__name__}: "
                          f"{value}")
                return "SPEC_FAILURE"
            if value != self.oracles[n]:
                self.fail(f"speculate round: q{n} returned WRONG rows "
                          f"(duplicate/missing under speculation)")
                return "WRONG_RESULT"
            if speculation_totals()["wins"] <= before:
                self.fail("speculate round: the straggler hung but no "
                          "speculative attempt won")
                return "NO_SPEC_WIN"
            time.sleep(2.0)  # let the hung loser wake and self-abort
            return "match+spec_win"
        if op == "hang_deadline":
            step["site"], step["spec"] = \
                "statement.execute", "hang(1500):once"
            cluster.arm(step["site"], step["spec"])
            try:
                StatementClient(cluster.statement.url, "SELECT 3",
                                deadline_s=0.7).drain()
                return "NO_TIMEOUT"
            except QueryError as e:
                outcome = f"clean_failure:{e.error_name}"
            time.sleep(1.2)  # let the hung engine thread drain
            return outcome
        raise ValueError(op)

    # -- the soak ------------------------------------------------------

    def run(self) -> int:
        args = self.args
        queries = [int(x) for x in args.queries.split(",") if x.strip()]
        failpoints.disarm_all()
        totals0 = dict(failpoints.failpoint_totals())
        set_flight_recorder(FlightRecorder(
            dump_dir=tempfile.mkdtemp(prefix="presto_tpu_chaos_")))
        # invariant 4: the lock-order witness rides the whole soak --
        # every OrderedLock acquire on every tier is order-checked, and
        # ONE inversion anywhere fails the round that provoked it
        wlocks.reset_witness()
        wlocks.arm_witness()
        witness0 = wlocks.witness_violations_total()
        witness_seen = 0  # records consumed by per-round reporting
        cluster = ChaosCluster(self.sf, workers=args.workers)
        t_run0 = time.time()
        try:
            print(f"warming oracles for q{queries} at sf={self.sf} ...")
            self.warm(cluster, queries)
            schedule = build_schedule(args.seed, queries, args.schedule)
            prev_scrapes = cluster.scrapes()
            for i, step in enumerate(schedule):
                cluster.disarm_all()
                t0_us = int(time.time() * 1e6)
                if step["kind"] == "query":
                    cluster.arm(step["site"], step["spec"])
                    outcome = self.query_round(cluster, step)
                else:
                    outcome = self.op_round(cluster, step)
                # fault accounting leg 1: admin-API fire counts vs the
                # flight-recorder ring, while this round's arm is live
                doc = cluster.armed_doc()
                fires = doc["armed"].get(step["site"], {}).get("fires", 0)
                action = step["spec"].split(":")[0].split("(")[0]
                self.expected_fires[(step["site"], action)] = \
                    self.expected_fires.get((step["site"], action), 0) \
                    + fires
                ring = ring_fires_since(t0_us, step["site"])
                if ring != fires:
                    self.fail(
                        f"round {i}: {step['site']} fired {fires} but "
                        f"the flight ring recorded {ring}")
                # invariant 2: counters never decrease, audited from
                # real scrapes every round
                scrapes = cluster.scrapes()
                for ep in scrapes:
                    for v in monotonicity_violations(prev_scrapes[ep],
                                                     scrapes[ep]):
                        self.fail(f"round {i}: counter decreased on "
                                  f"{ep}: {v}")
                prev_scrapes = scrapes
                # invariant 4: no lock-order inversion this round (the
                # witness catches the FIRST inconsistent acquisition
                # deterministically; which round provoked it is part
                # of the failure report)
                wnow = wlocks.witness_violations_total()
                if wnow != witness0:
                    # only the records NEW since the last round: each
                    # inversion is attributed to (and fails) exactly
                    # the round that provoked it
                    for v in wlocks.witness_violations()[witness_seen:]:
                        self.fail(
                            f"round {i}: lock-order inversion: "
                            f"acquired {v['acquiring']} while holding "
                            f"{v['held']} at {v['site']} (established "
                            f"order {' -> '.join(v['reversePath'])} "
                            f"from {v['reverseSite']})")
                    witness_seen = len(wlocks.witness_violations())
                    witness0 = wnow
                row = {"round": i, "kind": step["kind"],
                       "layer": step["layer"],
                       "site": step["site"], "spec": step["spec"],
                       "fires": fires, "outcome": outcome}
                if step["kind"] == "query":
                    row["query"] = step["query"]
                else:
                    row["op"] = step["op"]
                self.rounds.append(row)
                print(f"  round {i:2d} [{step['layer']:10s}] "
                      f"{step['site']}={step['spec']} fires={fires} "
                      f"-> {outcome}")
                if outcome in ("UNFIRED", "NOT_RECOVERED", "NO_TIMEOUT"):
                    # op-round regressions (broken recovery, broken
                    # client deadline) must fail the gate, not just
                    # print an odd-looking row
                    self.fail(f"round {i}: {step['site']} outcome "
                              f"{outcome}")
                if fires == 0:
                    self.fail(f"round {i}: {step['site']} never fired "
                              f"(site unreachable in this schedule)")
            cluster.disarm_all()
            # fault accounting leg 2: lifetime registry/metrics totals
            reg_delta = {}
            for key, v in failpoints.failpoint_totals().items():
                d = v - totals0.get(key, 0)
                if d:
                    reg_delta[key] = d
            if reg_delta != self.expected_fires:
                self.fail(f"registry fire totals {reg_delta} != "
                          f"per-round accounting {self.expected_fires}")
            scraped = failpoint_counter_totals(
                cluster.scrapes()["worker0"])
            for key, want in self.expected_fires.items():
                have = scraped.get(key, 0) - totals0.get(key, 0)
                if have != want:
                    self.fail(f"/v1/metrics hit counter for {key} is "
                              f"{have}, expected {want}")
            # coverage: the acceptance floor for a smoke run
            fired_layers = {r["layer"] for r in self.rounds
                            if r["fires"] > 0}
            fired_sites = {r["site"] for r in self.rounds
                           if r["fires"] > 0}
            need = {"exchange", "serde", "task", "memory", "discovery"}
            if len(fired_sites) < 5 or not need <= fired_layers:
                self.fail(f"coverage floor missed: {len(fired_sites)} "
                          f"sites over layers {sorted(fired_layers)}")
        finally:
            failpoints.disarm_all()
            wlocks.disarm_witness()
            cluster.stop()
        return self.report(time.time() - t_run0, queries)

    def report(self, wall_s: float, queries) -> int:
        determinism = {"seed": self.args.seed, "sf": self.sf,
                       "queries": queries, "rounds": self.rounds}
        digest = hashlib.sha256(json.dumps(
            determinism, sort_keys=True).encode()).hexdigest()[:16]
        doc = {"determinism": determinism, "digest": digest,
               "invariants": {
                   "correct_or_clean": not any(
                       "WRONG" in r["outcome"] or r["outcome"] in
                       ("HUNG", "NOT_RECOVERED", "NO_TIMEOUT", "UNFIRED",
                        "UNDETECTED", "NO_FLIGHT_EVENT", "NOT_DEMOTED",
                        "NO_SPEC_WIN", "SPEC_FAILURE",
                        "UNREPLAYED_PAGES", "UNACCOUNTED_COLLAPSE",
                        "UNACCOUNTED_DEGRADATION",
                        "NOT_DEGRADED_TO_TOTALS")
                       for r in self.rounds),
                   "no_counter_decrease": not any(
                       "counter decreased" in f for f in self.failures),
                   "fault_accounting": not any(
                       "accounting" in f or "hit counter" in f
                       or "flight" in f for f in self.failures),
                   "lock_order": not any(
                       "lock-order inversion" in f
                       for f in self.failures)},
               "violations": self.failures,
               "wallSeconds": round(wall_s, 2)}
        path = self.args.report or os.path.join(
            tempfile.gettempdir(),
            f"presto_tpu_chaos_seed{self.args.seed}.json")
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        ok = not self.failures
        print(f"chaos: {len(self.rounds)} rounds, "
              f"{sum(r['fires'] for r in self.rounds)} faults fired, "
              f"digest {digest}, {wall_s:.1f}s -> "
              f"{'OK' if ok else 'INVARIANT VIOLATIONS'}")
        print(f"report: {path}")
        return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="chaos")
    ap.add_argument("--seed", type=int, default=42,
                    help="schedule + trigger seed (default 42)")
    ap.add_argument("--queries", default="",
                    help="comma-separated TPC-H numbers (default: "
                         "smoke/full preset)")
    ap.add_argument("--schedule", type=int, default=0,
                    help="total rounds (0 = the coverage prefix only)")
    ap.add_argument("--smoke", action="store_true",
                    help="small committed schedule (<60s): the "
                         "lint_all.sh pre-PR gate")
    ap.add_argument("--sf", type=float, default=0.01)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=60.0,
                    help="per-query coordinator deadline (watchdog "
                         "adds 30s)")
    ap.add_argument("--report", default="",
                    help="JSON report path (default: under $TMPDIR)")
    args = ap.parse_args(argv)
    if not args.queries:
        args.queries = ",".join(
            str(q) for q in (SMOKE_QUERIES if args.smoke
                             else FULL_QUERIES))
    try:
        return ChaosRun(args).run()
    except KeyboardInterrupt:
        raise
    except Exception as e:  # noqa: BLE001 - harness error, not verdict
        import traceback
        traceback.print_exc()
        print(f"chaos: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
