#!/usr/bin/env python
"""tpulint: presto-tpu's static-analysis gate. Run before sending a PR.

Thin launcher over ``presto_tpu.lint.cli`` -- see that module for the
exit-code contract and DESIGN.md ("tpulint") for the pass catalog,
suppression syntax (``# tpulint: disable=H001``), and baseline policy
(``tpulint_baseline.json``).

    python scripts/tpulint.py                 # repo gate (CI runs this)
    python scripts/tpulint.py --json          # stable machine output
    python scripts/tpulint.py --list-passes
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from presto_tpu.lint.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
