#!/usr/bin/env python
"""DEPRECATED static check: hot-path kernel modules stay narrow-lane
disciplined. Use ``python scripts/tpulint.py --select W001`` instead.

THIN SHIM over tpulint's W001 pass (presto_tpu/lint/passes/
wide_lanes.py) -- the check that started as this standalone script in
PR 2 now lives in the pluggable framework, with coverage extended to
join.py/sort.py/window.py. Importing it emits a DeprecationWarning;
the entry point keeps the original contract for existing callers and
tests/test_no_wide_lanes.py:

  * ``HOT_MODULES`` / ``WIDE_OK_FUNCS`` module globals (mutable -- the
    sensitivity test empties the whitelist);
  * ``check_file(path) -> [(lineno, message)]``;
  * ``check_all() -> [\"rel:line: message\"]`` sorted;
  * ``main()`` exits 1 with a report on violation.

Prefer ``python scripts/tpulint.py`` (runs W001 over the full module
set plus the other passes) for anything new.
"""

from __future__ import annotations

import os
import sys
import warnings
from typing import List, Tuple

warnings.warn("scripts/check_no_wide_lanes.py is deprecated: run "
              "`python scripts/tpulint.py --select W001` (full module "
              "coverage + baseline/suppression support)",
              DeprecationWarning, stacklevel=2)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from presto_tpu.lint.core import ModuleSource  # noqa: E402
from presto_tpu.lint.passes import wide_lanes as _w  # noqa: E402

# original (PR 2) coverage; tpulint's W001 additionally covers
# join.py/sort.py/window.py
HOT_MODULES = (
    os.path.join("presto_tpu", "ops", "aggregation.py"),
    os.path.join("presto_tpu", "ops", "keys.py"),
)

# live view of the framework's whitelist for the shim's modules;
# reassigning this module global changes what check_file/check_all use
# (the sensitivity test relies on that)
WIDE_OK_FUNCS = {
    "aggregation.py": set(_w.WIDE_OK_FUNCS["aggregation.py"]),
    "keys.py": set(_w.WIDE_OK_FUNCS["keys.py"]),
}


def check_file(path: str) -> List[Tuple[int, str]]:
    rel = os.path.relpath(os.path.join(REPO, path), REPO) \
        if not os.path.isabs(path) else os.path.relpath(path, REPO)
    ms = ModuleSource(rel, repo=REPO)
    allowed = WIDE_OK_FUNCS.get(ms.basename, set())
    return [(f.line, f"{f.context}: {f.message}")
            for f in _w.scan_module(ms, whitelist=allowed)]


def check_all() -> List[str]:
    out: List[str] = []
    for rel in HOT_MODULES:
        for lineno, msg in check_file(rel):
            out.append(f"{rel}:{lineno}: {msg}")
    return sorted(out)


def main() -> int:
    violations = check_all()
    if violations:
        print("wide-lane violations in hot-path kernel modules:")
        for v in violations:
            print("  " + v)
        return 1
    print("no wide-lane violations "
          f"({', '.join(os.path.basename(m) for m in HOT_MODULES)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
