#!/usr/bin/env python
"""Static check: hot-path kernel modules stay narrow-lane disciplined.

Narrow-width execution (plan/widths.py, PERF.md roofline) depends on
the hot-path kernels never silently re-widening lanes: on v5e an int64
lane is emulated as an i32 pair, so one accidental wide array doubles
the HBM traffic the whole PR exists to remove. Two rules over
`ops/aggregation.py` and `ops/keys.py`:

  1. IMPLICIT-DTYPE array creation is banned everywhere: under jax x64
     (this engine enables it) `jnp.arange(n)` silently makes int64
     lanes and `jnp.zeros(n)` float64 lanes. Every zeros/ones/full/
     empty/arange/iota call must name its dtype.
  2. EXPLICIT int64 construction (`dtype=jnp.int64` / `.astype(
     jnp.int64)` / `jnp.int64(...)`) is allowed only inside the
     whitelisted limb-widening/accumulator functions -- the sites where
     64-bit math is the exactness contract, not an accident.

Run directly (exit 1 + report on violation) or through the tier-1
suite (tests/test_no_wide_lanes.py).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HOT_MODULES = (
    os.path.join("presto_tpu", "ops", "aggregation.py"),
    os.path.join("presto_tpu", "ops", "keys.py"),
)

# array constructors that default to wide lanes under jax x64
_CREATORS = {"zeros", "ones", "full", "empty", "arange",
             "broadcasted_iota", "iota"}

# functions where 64-bit lanes are the exactness contract: limb
# widening at accumulation, int64/int128 state tables, order-word
# reductions. New int64 in any OTHER hot-path function fails the check.
WIDE_OK_FUNCS = {
    "aggregation.py": {
        # limb-widening / exact-accumulation sites
        "_fused_limb_sums", "_limb_matmul_sum", "_seg_add", "_seg_count",
        "_sum128", "_SegSumPool.add", "_seg_total", "_padded_cumsum",
        # int64 state tables / finalizers (G-sized, not row-sized)
        "_acc_columns", "_sorted_states", "finalize_states",
        "finalize_variance", "hll_estimate", "_group_by_sorted",
        # order-word / argbest reductions (uint64 words, int64 row ids)
        "_argbest", "_hll_registers_from_values", "_seg_scan_extreme",
        "_seg_extreme_at",
        # planner-facing glue
        "group_by", "merge_partials",
    },
    # keys.py widens VALUES to uint64 order words by design; int64
    # appears only as the cast-through in _fixed_words
    "keys.py": {"_fixed_words", "key_words", "_string_words"},
}


def _func_name(stack: List[str]) -> str:
    return ".".join(stack[-2:]) if len(stack) > 1 else \
        (stack[0] if stack else "<module>")


def _is_int64_attr(node: ast.AST) -> bool:
    return isinstance(node, ast.Attribute) and node.attr in ("int64",)


def check_file(path: str) -> List[Tuple[int, str]]:
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    base = os.path.basename(path)
    allowed = WIDE_OK_FUNCS.get(base, set())
    violations: List[Tuple[int, str]] = []
    stack: List[str] = []

    class V(ast.NodeVisitor):
        def _in_allowed(self) -> bool:
            name = _func_name(stack)
            return name in allowed or (stack and stack[0] in allowed)

        def visit_FunctionDef(self, node):
            stack.append(node.name)
            self.generic_visit(node)
            stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            stack.append(node.name)
            self.generic_visit(node)
            stack.pop()

        def visit_Call(self, node):
            fn = node.func
            # rule 1: jnp/np array creators must name a dtype
            if isinstance(fn, ast.Attribute) and fn.attr in _CREATORS \
                    and isinstance(fn.value, ast.Name) \
                    and fn.value.id in ("jnp", "np"):
                has_dtype = any(k.arg == "dtype" for k in node.keywords)
                # zeros/ones/full/empty: dtype may ride positionally
                # (full(shape, fill, dtype); arange(n, dtype=...))
                if not has_dtype and fn.attr == "full" \
                        and len(node.args) >= 3:
                    has_dtype = True
                if not has_dtype:
                    violations.append(
                        (node.lineno,
                         f"{_func_name(stack)}: jnp.{fn.attr}() without "
                         f"an explicit dtype (implicit wide lanes under "
                         f"x64)"))
            # rule 2: explicit int64 outside the whitelist
            if _is_int64_attr(fn) and not self._in_allowed():
                violations.append(
                    (node.lineno,
                     f"{_func_name(stack)}: jnp.int64(...) outside the "
                     f"whitelisted limb-widening sites"))
            if isinstance(fn, ast.Attribute) and fn.attr == "astype" \
                    and node.args and _is_int64_attr(node.args[0]) \
                    and not self._in_allowed():
                violations.append(
                    (node.lineno,
                     f"{_func_name(stack)}: .astype(int64) outside the "
                     f"whitelisted limb-widening sites"))
            self.generic_visit(node)

        def visit_keyword(self, node):
            if node.arg == "dtype" and _is_int64_attr(node.value) \
                    and not self._in_allowed():
                violations.append(
                    (getattr(node.value, "lineno", 0),
                     f"{_func_name(stack)}: dtype=int64 outside the "
                     f"whitelisted limb-widening sites"))
            self.generic_visit(node)

    V().visit(tree)
    return violations


def check_all() -> List[str]:
    out: List[str] = []
    for rel in HOT_MODULES:
        path = os.path.join(REPO, rel)
        for lineno, msg in check_file(path):
            out.append(f"{rel}:{lineno}: {msg}")
    return sorted(out)


def main() -> int:
    violations = check_all()
    if violations:
        print("wide-lane violations in hot-path kernel modules:")
        for v in violations:
            print("  " + v)
        return 1
    print("no wide-lane violations "
          f"({', '.join(os.path.basename(m) for m in HOT_MODULES)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
