#!/usr/bin/env python
"""Opportunistic TPU-relay watcher (VERDICT round-3 item 2).

The remote-TPU relay ("axon" platform) has been down for whole rounds;
a single capture attempt at bench time therefore records nothing. This
watcher probes the tunnel every RELAY_WATCH_INTERVAL seconds for up to
RELAY_WATCH_HOURS, appending one line per attempt to
chip_evidence/relay_attempts.log; the moment a probe succeeds it runs
`bench.py --full --no-retry`, which persists a timestamped chip-evidence
JSON under chip_evidence/. After a successful capture it keeps watching
at a lower cadence (fresh evidence beats stale evidence, and the tunnel
can flap), but never re-captures more than once per hour.

Run it in the background at the start of a round:
    nohup python scripts/relay_watch.py >> chip_evidence/relay_watch.out &
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")
sys.path.insert(0, REPO)
from bench import _log_attempt as log  # one writer, one log format

INTERVAL_S = float(os.environ.get("RELAY_WATCH_INTERVAL", "900"))
HOURS = float(os.environ.get("RELAY_WATCH_HOURS", "11"))
PROBE_TIMEOUT_S = float(os.environ.get("RELAY_WATCH_PROBE_TIMEOUT", "60"))
RECAPTURE_MIN_GAP_S = 3600.0


def probe() -> bool:
    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    env["BENCH_PROBE"] = "1"
    try:
        p = subprocess.run([sys.executable, BENCH], capture_output=True,
                           text=True, timeout=PROBE_TIMEOUT_S, env=env)
        return any(l.startswith("{") for l in p.stdout.splitlines())
    except subprocess.TimeoutExpired:
        return False


def main() -> int:
    deadline = time.time() + HOURS * 3600
    last_capture = 0.0
    log("WATCH-START",
        f"interval={INTERVAL_S:.0f}s hours={HOURS:g}")
    while time.time() < deadline:
        if probe():
            if time.time() - last_capture >= RECAPTURE_MIN_GAP_S:
                log("UP", "watcher: capturing full suite")
                p = subprocess.run([sys.executable, BENCH, "--full",
                                    "--no-retry"],
                                   capture_output=True, text=True)
                ok = False
                for l in p.stdout.splitlines():
                    if l.startswith("{"):
                        ok = json.loads(l).get("detail", {}).get("scoring")
                log("CAPTURE-" + ("OK" if ok else "FAILED"))
                last_capture = time.time()
            # captured recently: idle at the normal cadence
        else:
            log("DOWN", "watcher probe")
        time.sleep(INTERVAL_S)
    log("WATCH-END")
    return 0


if __name__ == "__main__":
    sys.exit(main())
