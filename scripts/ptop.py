#!/usr/bin/env python
"""ptop: a polling terminal dashboard over ``GET /v1/cluster``.

The live counterpart of scripts/scrape_metrics.py: point it at a
statement tier and it renders, once per interval,

  * a cluster header -- uptime, workers alive/configured, queued/
    running/blocked query counts, live tasks, aggregate rows/s, stuck
    firings;
  * one progress bar per in-flight query (state, stage, rows, percent,
    last-advance age -- the bar stalls visibly when progress does);
  * one row per worker (state, running tasks, memory occupancy,
    uptime).

  python scripts/ptop.py http://127.0.0.1:8080             # live loop
  python scripts/ptop.py URL --interval 1
  python scripts/ptop.py URL --once                        # one frame
  python scripts/ptop.py URL --once --json                 # tests/CI

``--once --json`` prints the raw cluster document (plus a ``fetchedAt``
stamp) and exits 0 -- the machine-readable mode the test suite golden-
shapes. Exit codes: 0 ok, 2 endpoint unreachable.
"""

import argparse
import json
import os
import sys
import time
import urllib.request

# repo root importable regardless of invocation directory
sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def fetch_cluster(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(f"{url.rstrip('/')}/v1/cluster",
                                timeout=timeout) as r:
        return json.loads(r.read().decode())


def _bar(pct: float, width: int = 24) -> str:
    filled = int(round(min(max(pct, 0.0), 100.0) / 100.0 * width))
    return "[" + "#" * filled + " " * (width - filled) + "]"


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}TB"


def render(doc: dict) -> str:
    """One dashboard frame as text (pure function of the document, so
    tests can golden it without a terminal)."""
    lines = []
    q = doc.get("queries", {})
    fleet = ""
    if doc.get("workersDraining"):
        fleet += f" ({doc['workersDraining']} draining)"
    if doc.get("workersDead"):
        fleet += f" ({doc['workersDead']} DEAD)"
    lines.append(
        f"presto-tpu cluster  up {doc.get('uptimeSeconds', 0):.0f}s  "
        f"workers {doc.get('workersAlive', 0)}/"
        f"{doc.get('workersConfigured', 0)}{fleet}  "
        f"queries q:{q.get('queued', 0)} r:{q.get('running', 0)} "
        f"b:{q.get('blocked', 0)}  "
        f"done {q.get('finishedTotal', 0)}+{q.get('failedTotal', 0)}f  "
        f"tasks {doc.get('liveTasks', 0)}  "
        f"{doc.get('rowsPerSecond', 0):.0f} rows/s  "
        f"stuck {doc.get('stuckQueriesTotal', 0)}")
    # cluster staging rate (the data-path waterfall's device_put hop:
    # host->HBM GB/s) + the bottleneck hop when ceilings were probed
    dp = doc.get("datapath") or {}
    if dp:
        bn = dp.get("bottleneck")
        lines.append(
            f"staging {dp.get('stagingGbPerS', 0.0):.3f} GB/s"
            + (f"  bottleneck {bn}" if bn else ""))
    # estimate-accuracy roll-up (exec/accuracy.py): how many nodes were
    # scored, how many missed the band, and the worst offender so far
    acc = doc.get("accuracy") or {}
    if acc:
        worst = acc.get("worstNode")
        lines.append(
            f"accuracy {acc.get('records', 0)} records  "
            f"misest {acc.get('misestimates', 0)}  "
            f"worst q {acc.get('worstQError', 0.0):.2f}x"
            + (f" ({worst})" if worst else ""))
    # execution-timeline occupancy roll-up (exec/timeline.py): the last
    # query's host/device overlap fraction and device-idle wall --
    # zero overlap reads "the pipeline ran strictly serial"
    tl = doc.get("timeline") or {}
    if tl:
        lines.append(
            f"occupancy overlap {tl.get('overlapFraction', 0.0):.0%}  "
            f"device idle {tl.get('deviceIdleUs', 0) / 1000.0:.1f}ms  "
            f"intervals {tl.get('intervals', 0)} "
            f"({tl.get('dropped', 0)} dropped)")
    lines.append("-" * 78)
    running = doc.get("runningQueries", [])
    if not running:
        lines.append("(no queries in flight)")
    for rq in running:
        prog = rq.get("progress") or {}
        pct = float(prog.get("progressPercent", 0.0))
        age = prog.get("lastAdvanceAgeMs")
        age_s = f" adv {age / 1000.0:.1f}s ago" if age is not None \
            else ""
        # straggler-mitigation provenance: speculative copies racing
        # their originals show beside the bar
        spec = prog.get("speculativeTasks", 0)
        spec_s = f" spec:{spec}" if spec else ""
        # achieved GB/s: the query's cumulative processed bytes over
        # its TOTAL elapsed wall (queue + compile included) -- a
        # processed-bytes throughput, coarser than the per-hop rates
        # /v1/datapath serves, but live per query
        gbps = float(prog.get("bytes", 0)) / \
            max(float(rq.get("elapsedMs", 0)) / 1000.0, 1e-3) / 1e9
        # worst q-error of THIS query (filled at finalize, so running
        # queries show "-" until their accuracy ledger lands)
        mq = rq.get("maxQError")
        mq_s = f"{float(mq):5.1f}x" if mq is not None else "     -"
        lines.append(
            f"{rq.get('queryId', '?'):<26} {rq.get('state', '?'):<9} "
            f"{_bar(pct)} {pct:5.1f}%  "
            f"{prog.get('stage', '-'):<8} "
            f"rows {int(prog.get('rows', 0)):>10,} "
            f"{gbps:6.3f}GB/s q{mq_s}{age_s}{spec_s}")
        lines.append(f"  {rq.get('query', '')[:74]}")
    lines.append("-" * 78)
    # resource-group rows (latency-class admission): per-group queue
    # depth beside the batching executor's dispatch amortization
    groups = doc.get("resourceGroups") or {}
    for name in sorted(groups):
        g = groups[name]
        lines.append(
            f"group {name:<20} r:{g.get('running', 0):>3}"
            f"/{g.get('hardConcurrencyLimit', 0):<3} "
            f"q:{g.get('queued', 0):>4}/{g.get('maxQueued', 0):<4} "
            f"w:{g.get('schedulingWeight', 1):<2} "
            f"prio:{g.get('priority', 0)}")
    batching = doc.get("batching") or {}
    if batching:
        lines.append(
            f"batching: {batching.get('queriesBatched', 0)} queries / "
            f"{batching.get('batchesDispatched', 0)} dispatches "
            f"(occ last {batching.get('lastBatchSize', 0)} "
            f"avg {batching.get('avgOccupancy', 0.0):.1f} "
            f"max {batching.get('maxBatchSize', 0)})  "
            f"solo {batching.get('soloDispatches', 0)}  "
            f"collapses {sum((batching.get('collapses') or {}).values())}")
        lines.append("-" * 78)
    workers = doc.get("workers", [])
    if not workers:
        lines.append("(no workers configured: embedded engine)")
    for w in workers:
        mem = w.get("memory", {})
        # the elastic fleet state machine (ACTIVE | DRAINING | DRAINED
        # | DEAD), falling back to the legacy flat state for old nodes
        state = w.get("fleetState", w.get("state", "?"))
        lines.append(
            f"{w.get('nodeId', w.get('uri', '?')):<26} "
            f"{state:<13} "
            f"tasks {w.get('runningTasks', w.get('activeTasks', 0)):>3} "
            f" mem {_fmt_bytes(mem.get('reservedBytes', 0))}/"
            f"{_fmt_bytes(mem.get('capacityBytes', 0))} "
            f"(peak {_fmt_bytes(mem.get('peakBytes', 0))})  "
            f"up {w.get('uptimeSeconds', 0):.0f}s")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ptop")
    ap.add_argument("url", help="statement-tier base URL")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="seconds between frames (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit")
    ap.add_argument("--json", action="store_true",
                    help="with --once: print the raw cluster document "
                         "as JSON (the machine-readable mode)")
    args = ap.parse_args(argv)

    while True:
        try:
            doc = fetch_cluster(args.url)
        except Exception as e:  # noqa: BLE001 - endpoint down IS the news
            print(f"error: cannot fetch {args.url}/v1/cluster: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2
        if args.once and args.json:
            print(json.dumps({"fetchedAt": time.time(), **doc},
                             indent=1, sort_keys=True))
            return 0
        if not args.once:
            # ANSI clear + home: a cheap full-frame repaint
            sys.stdout.write("\x1b[2J\x1b[H")
        print(render(doc))
        if args.once:
            return 0
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
