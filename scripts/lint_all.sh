#!/usr/bin/env bash
# The whole pre-PR gate in one invocation: tpulint (AST tier), then
# kernaudit (IR tier over the TPC-H q1-q22 corpus), then a seeded
# chaos smoke (scripts/chaos.py --smoke: a small deterministic fault
# schedule over an in-process cluster, so every recovery path runs
# before every PR), then the loadgen smoke (batching must form batches
# and beat serial dispatch), then perfgate (the committed BENCH +
# LOADGEN trajectories vs PERF_BASELINE.json noise bands), preserving
# the repo's shared exit contract:
#
#   0  all gates clean
#   1  findings / stale baseline entries / invariant violations
#   2  internal error in any gate (bad path, failed staging, ...)
#
# Extra arguments are forwarded to the two LINT tools only (e.g.
# --format github for CI annotations, --json for machine output); the
# chaos smoke always runs its committed seed-42 schedule. Runs every
# gate even when an earlier one fails, so one CI run reports all.
set -u

here="$(cd "$(dirname "$0")" && pwd)"

rc=0
# the default (no --select) run is EVERY registered pass: wide lanes /
# host syncs / retrace keys / concurrency C001-C004 / swallowed errors
# AND the allocation tier M001-M003 (unbounded accumulation, unreserved
# materialization, copy amplification) -- all against the one committed
# baseline, which stays EMPTY for the M/C families (fix, don't baseline)
python "$here/tpulint.py" "$@"
t=$?
[ "$t" -gt "$rc" ] && rc=$t

# the concurrency artifact gate: the CURRENT lock-acquisition-order
# graph must be cycle-free (exit 2 -- a potential deadlock is never
# committable) and structurally identical to the committed
# LOCK_ORDER.json (exit 1 -- run scripts/lockgraph.py --update and
# review the diff). tpulint above already ran C001-C004 over the same
# surface; this gate pins the REVIEWED artifact.
python "$here/lockgraph.py" --check
o=$?
[ "$o" -gt "$rc" ] && rc=$o

# the corpus gate audits the IR the engine actually dispatches:
# pipeline-region fusion ON, so fused jaxprs are what K001-K007 walk
# (K006 donation-safety proofs + K007 baked-constant bloat included)
PRESTO_TPU_FUSION=1 python "$here/kernaudit.py" "$@"
k=$?
[ "$k" -gt "$rc" ] && rc=$k

python "$here/chaos.py" --seed 42 --smoke
c=$?
[ "$c" -gt "$rc" ] && rc=$c

# the throughput-tier tripwire: batches must still form and batched
# dispatch must still beat the serial A/B control on a small fixed
# zipfian workload (the committed LOADGEN_r*.json artifacts gate the
# real numbers through perfgate below)
python "$here/loadgen.py" --smoke
l=$?
[ "$l" -gt "$rc" ] && rc=$l

python "$here/perfgate.py" --json
g=$?
[ "$g" -gt "$rc" ] && rc=$g

exit "$rc"
