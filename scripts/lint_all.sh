#!/usr/bin/env bash
# The whole static-analysis gate in one invocation: tpulint (AST tier)
# then kernaudit (IR tier over the TPC-H q1-q22 corpus), preserving the
# repo's shared exit contract:
#
#   0  both gates clean
#   1  findings / stale baseline entries in either gate
#   2  internal error in either gate (bad path, failed staging, ...)
#
# Extra arguments are forwarded to BOTH tools (e.g. --format github for
# CI annotations, --json for machine output). Runs both even when the
# first fails, so one CI run reports everything.
set -u

here="$(cd "$(dirname "$0")" && pwd)"

rc=0
python "$here/tpulint.py" "$@"
t=$?
[ "$t" -gt "$rc" ] && rc=$t

python "$here/kernaudit.py" "$@"
k=$?
[ "$k" -gt "$rc" ] && rc=$k

exit "$rc"
