"""Root-cause harness for the r01->r04 hand-built-q1 CPU delta.

Times the SAME staged data through three q1 kernel variants at HEAD:
  exact128   the shipped plan (sums -> decimal(38,x) = int128 13-bit
             limb exact accumulation, round-2+ behavior)
  int64acc   sums -> decimal(18,x) (int64 accumulation -- the round-1
             representation, exactness waived)
  f64acc     sums -> double (pure float64 accumulate, lower bound)

Run with scripts/_cpu.py armor (relay may be down):
    python scripts/bench_bisect.py [sf] [iters]
"""

import json
import sys
import time

import os
_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, _HERE)
import _cpu  # noqa: F401  (must precede jax)

import jax
import numpy as np


def build_variant(kind):
    from presto_tpu import types as T
    from presto_tpu.expr import (call, compile_filter, compile_projections,
                                 const, input_ref)
    from presto_tpu.ops.aggregation import AggSpec, group_by

    D2 = T.decimal(12, 2)
    rf, ls = input_ref(0, T.char(1)), input_ref(1, T.char(1))
    qty, price = input_ref(2, D2), input_ref(3, D2)
    disc, tax = input_ref(4, D2), input_ref(5, D2)
    ship = input_ref(6, T.DATE)
    one = const(100, D2)
    filt = compile_filter(call("le", T.BOOLEAN, ship,
                               const("1998-09-02", T.DATE)))
    if kind == "f64acc":
        fp = T.DOUBLE

        def asf(e):
            return call("cast", fp, e)
        disc_price = call("multiply", fp, asf(price),
                          call("subtract", fp, asf(one), asf(disc)))
        charge = call("multiply", fp, disc_price,
                      call("add", fp, asf(one), asf(tax)))
        proj = compile_projections([rf, ls, asf(qty), asf(price),
                                    disc_price, charge, asf(disc)])
        sty = [fp] * 4
        avg = fp
    else:
        disc_price = call("multiply", T.decimal(24, 4), price,
                          call("subtract", D2, one, disc))
        charge = call("multiply", T.decimal(36, 6), disc_price,
                      call("add", D2, one, tax))
        proj = compile_projections([rf, ls, qty, price,
                                    disc_price, charge, disc])
        p = 38 if kind == "exact128" else 18
        sty = [T.decimal(p, 2), T.decimal(p, 2),
               T.decimal(p, 4), T.decimal(p, 6)]
        avg = D2
    aggs = [AggSpec("sum", 2, sty[0]), AggSpec("sum", 3, sty[1]),
            AggSpec("sum", 4, sty[2]), AggSpec("sum", 5, sty[3]),
            AggSpec("avg", 2, avg), AggSpec("avg", 3, avg),
            AggSpec("avg", 6, avg),
            AggSpec("count_star", None, T.BIGINT)]

    def run(batch):
        b = proj(filt(batch))
        return group_by(b, [0, 1], aggs, 16)

    return run


def main():
    sf = float(sys.argv[1]) if len(sys.argv) > 1 else 1.0
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 3

    from presto_tpu import types as _T  # noqa: F401 (warm import)
    from presto_tpu.block import batch_from_numpy
    from presto_tpu.connectors import tpch
    from presto_tpu.queries import Q1_COLUMNS

    n = tpch.table_row_count("lineitem", sf)
    capacity = -(-n // 1024) * 1024
    host = tpch.generate_columns("lineitem", sf, Q1_COLUMNS)
    schema = dict(tpch.TPCH_SCHEMA["lineitem"])
    tys = [schema[c] for c in Q1_COLUMNS]
    batch = jax.device_put(batch_from_numpy(
        tys, [host[c] for c in Q1_COLUMNS], capacity=capacity))
    jax.block_until_ready(batch)

    out = {"sf": sf, "rows": n, "iters": iters,
           "platform": jax.devices()[0].platform}

    def timed_on(fn, arg):
        t0 = time.time()
        jax.block_until_ready(fn(arg))
        compile_s = time.time() - t0
        best = float("inf")
        for _ in range(iters):
            t0 = time.time()
            jax.block_until_ready(fn(arg))
            best = min(best, time.time() - t0)
        return {"wall_s": round(best, 4), "compile_s": round(compile_s, 1),
                "rows_per_sec": round(n / best)}

    def timed(fn):
        return timed_on(fn, batch)

    for kind in ("exact128", "int64acc", "f64acc"):
        out[kind] = timed(jax.jit(build_variant(kind)))
        print(kind, out[kind], flush=True)

    # stage split on the shipped (exact128) shape: where does the time go?
    from presto_tpu import types as T
    from presto_tpu.expr import (call, compile_filter, compile_projections,
                                 const, input_ref)
    D2 = T.decimal(12, 2)
    rf, ls = input_ref(0, T.char(1)), input_ref(1, T.char(1))
    qty, price = input_ref(2, D2), input_ref(3, D2)
    disc, tax = input_ref(4, D2), input_ref(5, D2)
    ship = input_ref(6, T.DATE)
    one = const(100, D2)
    filt = compile_filter(call("le", T.BOOLEAN, ship,
                               const("1998-09-02", T.DATE)))
    disc_price = call("multiply", T.decimal(24, 4), price,
                      call("subtract", D2, one, disc))
    charge = call("multiply", T.decimal(36, 6), disc_price,
                  call("add", D2, one, tax))
    proj = compile_projections([rf, ls, qty, price, disc_price, charge,
                                disc])
    out["filter_project"] = timed(jax.jit(lambda b: proj(filt(b))))
    print("filter_project", out["filter_project"], flush=True)

    from presto_tpu.ops.aggregation import AggSpec, group_by
    aggs = [AggSpec("sum", 2, T.decimal(38, 2)),
            AggSpec("sum", 3, T.decimal(38, 2)),
            AggSpec("sum", 4, T.decimal(38, 4)),
            AggSpec("sum", 5, T.decimal(38, 6)),
            AggSpec("avg", 2, D2), AggSpec("avg", 3, D2),
            AggSpec("avg", 6, D2), AggSpec("count_star", None, T.BIGINT)]
    projected = jax.jit(lambda b: proj(filt(b)))(batch)
    jax.block_until_ready(projected)
    gb = jax.jit(lambda b: group_by(b, [0, 1], aggs, 16))

    out["group_by_only"] = timed_on(gb, projected)
    print("group_by_only", out["group_by_only"], flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
