#!/usr/bin/env python
"""loadgen: closed-loop concurrent-query benchmark of the throughput
tier (plan-fingerprint batching + latency-class admission).

N client threads drive the DISPATCH path -- latency-class resource
groups (``Dispatcher.with_latency_classes``) in front of the engine,
with the batching executor (exec/batching.py) in the executor seam
exactly where the statement tier mounts it -- using a zipfian query
mix over parameterized point lookups, dashboard aggregates and scans:
the "millions of users" workload shape, thousands of small queries
sharing a handful of plan fingerprints. Each run measures the SAME
seeded workload twice:

  * ``serial``  -- session ``query_batching=false`` (the A/B control:
    every query plans, stages and dispatches alone -- a cold literal
    pays its own XLA compile, the no-cross-query-amortization state
    the ROADMAP names);
  * ``batched`` -- batching on: co-batchable queries share one vmapped
    dispatch.

Latency attribution rides the existing histogram families: admission
waits land in ``presto_tpu_dispatch_queue_wait_seconds{group=...}``
per latency class (bucket-count deltas -> quantile_from_buckets, the
scrape-side arithmetic) and batch occupancy in
``presto_tpu_batch_occupancy_queries``; client-observed per-query
latency provides the end-to-end p50/p99.

  python scripts/loadgen.py --clients 100 --duration 10 --out LOADGEN_r01.json
  python scripts/loadgen.py --smoke              # lint_all.sh gate

``--smoke`` runs a small fixed workload and FAILS (exit 1) when
batching stops forming batches or stops beating serial dispatch -- the
cheap always-on regression tripwire; the committed LOADGEN_r*.json
artifacts gate the real numbers through scripts/perfgate.py
(qps down / p99_ms up beyond the noise band).

Exit codes: 0 ok, 1 smoke invariant violated, 2 harness error.
"""

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

# repo root importable + the shared CPU-forcing armor
sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _cpu  # noqa: E402,F401

from presto_tpu.exec.batching import (batching_totals,  # noqa: E402
                                      clear_batching,
                                      get_batching_executor,
                                      reset_batching_totals)
from presto_tpu.server.dispatcher import (Dispatcher,  # noqa: E402
                                          QueryRejected)
from presto_tpu.server.metrics import (get_histogram,  # noqa: E402
                                       quantile_from_buckets)

SF = 0.01

# the workload: (share, latency class, template text with {k}, key
# population). Populations sized to the sf=0.01 tables; a handful of
# fingerprints, many literals -- the batchable shape.
WORKLOAD = [
    (0.70, "interactive",
     "SELECT custkey, name, acctbal FROM customer WHERE custkey = {k}",
     1500),
    (0.25, "dashboard",
     "SELECT orderpriority, count(*) AS orders, sum(totalprice) AS s "
     "FROM orders WHERE custkey = {k} "
     "GROUP BY orderpriority ORDER BY orderpriority", 1500),
    (0.05, "batch",
     "SELECT sum(extendedprice * discount) FROM lineitem "
     "WHERE discount BETWEEN 0.05 AND 0.07 AND quantity < {k}", 30),
]


def zipf_cdf(n: int, s: float = 1.1) -> np.ndarray:
    """CDF of a zipfian rank distribution over keys 1..n."""
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), s)
    return np.cumsum(w / w.sum())


class Phase:
    """One closed-loop run: N clients, fixed wall-clock duration,
    every query admitted through the dispatcher's latency-class groups
    and executed through the batching-executor-or-serial seam."""

    QUEUE_HIST = "presto_tpu_dispatch_queue_wait_seconds"

    def __init__(self, dispatcher: Dispatcher, clients: int,
                 duration_s: float, seed: int, batching: bool,
                 window_ms: float):
        self.dispatcher = dispatcher
        self.clients = clients
        self.duration_s = duration_s
        self.seed = seed
        self.batching = batching
        self.window_ms = window_ms
        self.latencies = []   # (latency_s, class)
        self.errors = 0
        self.rejected = 0
        self._lock = threading.Lock()
        self._qid = [0]
        shares = np.cumsum([w[0] for w in WORKLOAD])
        self._shares = shares / shares[-1]
        self._cdfs = [zipf_cdf(w[3]) for w in WORKLOAD]

    def _one_query(self, rng) -> tuple:
        r = rng.random()
        wi = int(np.searchsorted(self._shares, r, side="left"))
        wi = min(wi, len(WORKLOAD) - 1)
        _, klass, template, _n = WORKLOAD[wi]
        key = int(np.searchsorted(self._cdfs[wi], rng.random()) + 1)
        return template.format(k=key), klass

    def _next_qid(self) -> str:
        with self._lock:
            self._qid[0] += 1
            return f"lg-{self.seed}-{self._qid[0]}"

    def _client(self, idx: int, deadline: float) -> None:
        from presto_tpu.sql import sql as run_sql
        executor = get_batching_executor()
        rng = np.random.default_rng(self.seed * 1000 + idx)
        base = {
            "query_batching": "true" if self.batching else "false",
            "batch_window_ms": str(self.window_ms),
            "batch_hot_min": "2",
        }
        while time.time() < deadline:
            text, klass = self._one_query(rng)
            sess = dict(base)
            sess["latency_class"] = klass
            qid = self._next_qid()

            def run(query_id, text=text, sess=sess):
                res = executor.try_execute(
                    text, sf=SF, session=sess, query_id=query_id)
                if res is not None:
                    return res
                return run_sql(text, sf=SF, session=sess,
                               query_id=query_id)

            t0 = time.time()
            rejected = False
            try:
                self.dispatcher.submit(
                    run, session={"user": f"client-{idx}", **sess},
                    query_text=text, query_id=qid, queue_timeout=120.0)
                ok = True
            except QueryRejected:
                # admission-to-SLO WORKING: the class queue is full
                # and the dispatcher sheds load instead of queueing
                # past the SLO -- counted, not an error
                ok, rejected = False, True
            except Exception:  # noqa: BLE001 - a failed query is an
                ok = False     # error sample, not a harness crash
            lat = time.time() - t0
            with self._lock:
                if ok:
                    self.latencies.append((lat, klass))
                elif rejected:
                    self.rejected += 1
                else:
                    self.errors += 1

    def _queue_hists(self):
        return {klass: get_histogram(self.QUEUE_HIST,
                                     {"group": f"global.{klass}"})
                for klass in ("interactive", "dashboard", "batch")}

    def run(self) -> dict:
        before = {k: h.snapshot() for k, h in self._queue_hists().items()}
        t0 = time.time()
        deadline = t0 + self.duration_s
        threads = [threading.Thread(target=self._client,
                                    args=(i, deadline), daemon=True)
                   for i in range(self.clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.duration_s + 300)
        wall = time.time() - t0
        after = {k: h.snapshot() for k, h in self._queue_hists().items()}
        lats = sorted(l for l, _ in self.latencies)
        n = len(lats)

        def pct(p):
            if not n:
                return 0.0
            return lats[min(int(p * n), n - 1)]

        per_class = {}
        queue_p99 = {}
        for klass in ("interactive", "dashboard", "batch"):
            delta = [b - a for a, b in zip(before[klass]["counts"],
                                           after[klass]["counts"])]
            queue_p99[klass] = round(quantile_from_buckets(
                before[klass]["buckets"], delta, 0.99) * 1e3, 2)
        for _, klass in self.latencies:
            per_class[klass] = per_class.get(klass, 0) + 1
        return {
            "queries": n,
            "errors": self.errors,
            "rejected": self.rejected,
            "wall_s": round(wall, 3),
            "qps": round(n / max(wall, 1e-9), 2),
            "p50_ms": round(pct(0.50) * 1e3, 2),
            "p99_ms": round(pct(0.99) * 1e3, 2),
            "queue_wait_p99_ms": queue_p99,
            "per_class": per_class,
        }


def engine_amortization(batch: int = 64, rounds: int = 8,
                        keypop: int = 32) -> dict:
    """Single-threaded engine-path A/B over the hot interactive
    template: N queries dispatched one-by-one on the serial path (warm
    plan cache -- the hot-literal best case) vs the SAME N queries as
    `rounds` direct batched dispatches. This isolates the per-query
    dispatch cost batching amortizes from the closed-loop numbers
    above, which also reflect host-side client/admission parallelism
    (a 24-core CPU control overlaps serial dispatches in a way one
    accelerator's program queue does not)."""
    from presto_tpu.sql import sql as run_sql
    ex = get_batching_executor()
    tpl = WORKLOAD[0][2]
    sess_off = {"query_batching": "false"}
    for k in range(1, keypop + 1):        # serial warm: per-literal
        run_sql(tpl.format(k=k), sf=SF,   # programs all compiled
                session=sess_off)
    ex.precompile(tpl.format(k=1), sf=SF, sizes=[batch])
    n = batch * rounds
    keys = [(i % keypop) + 1 for i in range(n)]
    t0 = time.time()
    for k in keys:
        run_sql(tpl.format(k=k), sf=SF, session=sess_off)
    serial_s = time.time() - t0
    t0 = time.time()
    for r in range(rounds):
        ex.bench_dispatch([tpl.format(k=k)
                           for k in keys[r * batch:(r + 1) * batch]],
                          sf=SF)
    batched_s = time.time() - t0
    return {"queries": n, "batch": batch, "key_population": keypop,
            "serial_qps": round(n / max(serial_s, 1e-9), 1),
            "batched_qps": round(n / max(batched_s, 1e-9), 1),
            "amortization": round(serial_s / max(batched_s, 1e-9), 2)}


def run_loadgen(clients: int, duration_s: float, seed: int,
                window_ms: float, engine_bench: bool = True) -> dict:
    """Warm + both measured phases over one dispatcher; returns the
    report document (the artifact's `detail`)."""
    from presto_tpu.sql import sql as run_sql
    clear_batching()
    dispatcher = Dispatcher.with_latency_classes(
        root_concurrency=max(clients, 16),
        root_queued=max(4 * clients, 64))
    # warm both paths' JIT caches so neither measured phase pays cold
    # compiles for the hot keys: one serial pass per template, then
    # every vmapped size bucket a batch of <= `clients` members can
    # land on (the power-of-two padding in exec/batching.py), then a
    # short unmeasured batched burst for the dispatch/event paths
    bucket_cap, sizes = 1, []
    while bucket_cap < min(clients, 64):
        bucket_cap *= 2
    s = 2
    while s <= bucket_cap:
        sizes.append(s)
        s *= 2
    executor = get_batching_executor()
    for _, _klass, template, _n in WORKLOAD:
        run_sql(template.format(k=1), sf=SF)
        executor.precompile(template.format(k=1), sf=SF, sizes=sizes)
    Phase(dispatcher, clients, 1.5, seed + 2,
          batching=True, window_ms=window_ms).run()
    # both measured phases draw the SAME seeded literal population --
    # the A/B controls for everything but the batching seam (per-phase
    # client pacing still differs: closed loop)
    serial = Phase(dispatcher, clients, duration_s, seed,
                   batching=False, window_ms=window_ms).run()
    reset_batching_totals()
    batched = Phase(dispatcher, clients, duration_s, seed,
                    batching=True, window_ms=window_ms).run()
    totals = batching_totals()
    avg_occ = (totals["batched_queries"] / totals["batches"]) \
        if totals["batches"] else 0.0
    speedup = batched["qps"] / max(serial["qps"], 1e-9)
    engine = engine_amortization() if engine_bench else None
    import jax
    return {
        "tier": "dispatch",
        "clients": clients,
        "duration_s": duration_s,
        "seed": seed,
        "mix": [{"share": w[0], "class": w[1], "template": w[2]}
                for w in WORKLOAD],
        "serial": serial,
        "batched": batched,
        "qps": batched["qps"],
        "p50_ms": batched["p50_ms"],
        "p99_ms": batched["p99_ms"],
        "serial_qps": serial["qps"],
        "serial_p99_ms": serial["p99_ms"],
        "speedup_qps": round(speedup, 2),
        "engine_dispatch": engine,
        "batching": {**totals, "avg_occupancy": round(avg_occ, 2)},
        "resource_groups": dispatcher.group_stats(),
        "platform": "cpu-fallback (loadgen)" if jax.devices()[0].platform
        == "cpu" else jax.devices()[0].platform,
        "sf": SF,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="loadgen",
        description="closed-loop concurrent-query benchmark "
                    "(batching + latency-class admission)")
    ap.add_argument("--clients", type=int, default=100)
    ap.add_argument("--duration", type=float, default=10.0,
                    help="seconds per phase (serial, then batched)")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--window-ms", type=float, default=10.0,
                    help="batch formation window for the batched phase")
    ap.add_argument("--smoke", action="store_true",
                    help="small fixed workload + invariant gate "
                         "(lint_all.sh); fails when batching stops "
                         "forming batches or stops beating serial")
    ap.add_argument("--out", default=None,
                    help="write a BENCH-schema LOADGEN artifact here")
    args = ap.parse_args(argv)

    clients = 12 if args.smoke else args.clients
    duration = 3.0 if args.smoke else args.duration
    try:
        detail = run_loadgen(clients, duration, args.seed,
                             args.window_ms,
                             engine_bench=not args.smoke)
    except Exception as e:  # noqa: BLE001 - harness failure is exit 2
        print(f"loadgen: harness error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    doc = {"parsed": {"metric": "loadgen_zipf_mix_qps",
                      "value": detail["qps"], "unit": "queries/s",
                      "detail": detail}}
    print(json.dumps(doc if not args.smoke else {
        "smoke": True,
        "serial_qps": detail["serial_qps"],
        "batched_qps": detail["qps"],
        "speedup_qps": detail["speedup_qps"],
        "p99_ms": detail["p99_ms"],
        "serial_p99_ms": detail["serial_p99_ms"],
        "avg_occupancy": detail["batching"]["avg_occupancy"],
    }, indent=1, sort_keys=True))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")

    if args.smoke:
        bad = []
        if detail["batching"]["batches"] < 1:
            bad.append("no batch ever formed")
        if detail["batching"]["avg_occupancy"] < 1.5:
            bad.append(f"avg occupancy "
                       f"{detail['batching']['avg_occupancy']} < 1.5")
        if detail["qps"] < 0.8 * detail["serial_qps"]:
            # 20% margin: a 3s closed-loop phase on a noisy CI runner
            # is not a precision instrument (the committed LOADGEN
            # artifacts gate real regressions through perfgate's noise
            # bands); the tripwire is for batching BREAKING, which
            # shows up as a multiple, not a few percent
            bad.append(f"batched qps {detail['qps']} below 0.8x serial "
                       f"{detail['serial_qps']}")
        if detail["batched"]["errors"] or detail["serial"]["errors"]:
            bad.append(f"query errors (serial "
                       f"{detail['serial']['errors']}, batched "
                       f"{detail['batched']['errors']})")
        for b in bad:
            print(f"loadgen: SMOKE VIOLATION: {b}", file=sys.stderr)
        return 1 if bad else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
