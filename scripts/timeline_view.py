#!/usr/bin/env python
"""Render execution timelines, or export them as a Chrome trace.

The reading end of the interval-ledger contract (exec/timeline.py):
point it at a tier's ``GET /v1/timeline`` (or a saved copy of that
document) and it prints each retained query's per-lane ASCII Gantt with
its occupancy summary and bubble verdict -- or, with ``--chrome``,
writes Chrome trace-event JSON loadable in Perfetto / chrome://tracing,
every span carrying the query's ``/v1/trace`` traceId in its args.

  python scripts/timeline_view.py http://127.0.0.1:8080
  python scripts/timeline_view.py http://127.0.0.1:8080 --chrome out.json
  python scripts/timeline_view.py timeline.json --query q-42

Exit codes: 0 rendered/exported, 1 no timelines, 2 source unreadable.
"""

import argparse
import json
import os
import sys
import urllib.request

# repo root importable regardless of invocation directory
sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from presto_tpu.exec.timeline import (TimelineSlice, ascii_gantt,  # noqa: E402
                                      bubble_verdict, occupancy,
                                      to_chrome_trace)


def load_doc(source: str, timeout: float = 5.0) -> dict:
    """A ``/v1/timeline`` document from a base URL or a saved file."""
    if source.startswith(("http://", "https://")):
        url = f"{source.rstrip('/')}/v1/timeline"
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read())
    with open(source) as f:
        return json.load(f)


def render(doc: dict, width: int = 48) -> str:
    """The per-query Gantt + occupancy readout (pure; tested)."""
    out = []
    queries = doc.get("queries") or {}
    for qid in sorted(queries):
        entry = queries[qid] or {}
        sl = TimelineSlice.from_json(entry.get("slice") or {}, now=0)
        out.append(f"== {qid}" + (f"  trace={entry['traceId']}"
                                  if entry.get("traceId") else ""))
        if sl.is_empty():
            out.append("  (no intervals retained)")
            continue
        out.extend(f"  {line}" for line in ascii_gantt(sl.intervals,
                                                       width=width))
        occ = occupancy(sl.intervals)
        if occ is not None:
            out.append(f"  wall={occ['wallUs']}us "
                       f"overlap={occ['overlapFraction']:.0%} "
                       f"device_idle={occ['deviceIdleUs']}us "
                       f"({occ['deviceIdleFraction']:.0%})")
            verdict = bubble_verdict(sl.intervals, occ)
            if verdict is not None:
                out.append(f"  verdict: {verdict['message']}")
    t = doc.get("totals") or {}
    out.append(f"queries={t.get('queries', 0)} "
               f"intervals={t.get('intervals', 0)} "
               f"dropped={t.get('dropped', 0)} "
               f"degraded={t.get('degraded', 0)}")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="timeline_view")
    ap.add_argument("source", help="tier base URL (fetches /v1/timeline) "
                                   "or a saved timeline JSON file")
    ap.add_argument("--chrome", default=None, metavar="OUT",
                    help="write Chrome trace-event JSON (Perfetto / "
                         "chrome://tracing) instead of the ASCII Gantt")
    ap.add_argument("--query", default=None,
                    help="render only this query id")
    ap.add_argument("--width", type=int, default=48)
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)

    try:
        doc = load_doc(args.source, timeout=args.timeout)
    except Exception as e:  # noqa: BLE001 - source unreadable is the signal
        print(f"error: cannot load {args.source}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    queries = doc.get("queries") or {}
    if args.query is not None:
        if args.query not in queries:
            print(f"error: no timeline for {args.query!r}; have: "
                  f"{sorted(queries) or 'none'}", file=sys.stderr)
            return 1
        doc = dict(doc, queries={args.query: queries[args.query]})
    if args.chrome is not None:
        trace = to_chrome_trace(doc)
        with open(args.chrome, "w") as f:
            json.dump(trace, f)
        spans = sum(1 for e in trace["traceEvents"]
                    if e.get("ph") == "X")
        print(f"wrote {args.chrome}: {spans} spans across "
              f"{len(queries)} queries")
        return 0 if spans else 1
    if not queries:
        print("no timelines retained", file=sys.stderr)
        return 1
    print(render(doc, width=args.width))
    return 0


if __name__ == "__main__":
    sys.exit(main())
