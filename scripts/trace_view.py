#!/usr/bin/env python
"""Render a stitched distributed trace as an ASCII waterfall.

The reading end of the one-trace-per-query contract: point it at a
coordinator's ``GET /v1/trace/{queryId}`` (or a worker's local-slice
endpoint, or a ``RecordingTracer.export_jsonl`` file) and it prints the
span tree on the trace's time axis with critical-path attribution --
"where did q1's 1.2s go?" answered from one artifact.

  python scripts/trace_view.py http://127.0.0.1:8080/v1/trace/20260730_ab12
  python scripts/trace_view.py http://127.0.0.1:8080 --query 20260730_ab12
  python scripts/trace_view.py spans.jsonl --trace query.deadbeef
  python scripts/trace_view.py spans.jsonl            # lists trace ids

Exit codes: 0 rendered, 1 trace not found / empty, 2 source unreadable.
"""

import argparse
import json
import os
import sys
import urllib.request

# repo root importable regardless of invocation directory
sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from presto_tpu.traceview import fetch_trace, render_waterfall  # noqa: E402


def load_jsonl(path: str, trace_id: str = None):
    """JSONL span export OR a saved ``/v1/trace/{queryId}`` document ->
    one trace doc (or the available ids when the file holds several
    traces and none was picked)."""
    by_trace = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            if "spanId" in doc:
                by_trace.setdefault(doc.get("traceId", "?"),
                                    []).append(doc)
            elif isinstance(doc.get("spans"), list):
                # a curl'd GET /v1/trace/{queryId} response saved whole
                for span in doc["spans"]:
                    by_trace.setdefault(doc.get("traceId", "?"),
                                        []).append(span)
    if trace_id is not None:
        spans = by_trace.get(trace_id)
        return {"traceId": trace_id, "spans": spans} if spans else None
    if len(by_trace) == 1:
        tid, spans = next(iter(by_trace.items()))
        return {"traceId": tid, "spans": spans}
    if not by_trace:
        return None
    return {"_ids": sorted(by_trace)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trace_view")
    ap.add_argument("source", help="trace URL, coordinator base URL "
                                   "(with --query), or spans JSONL file")
    ap.add_argument("--query", default=None,
                    help="query id: source is a coordinator/worker base "
                         "URL, fetch its /v1/trace/{query}")
    ap.add_argument("--trace", default=None,
                    help="trace id to pick out of a JSONL file")
    ap.add_argument("--width", type=int, default=72)
    args = ap.parse_args(argv)

    try:
        if args.source.startswith(("http://", "https://")):
            doc = fetch_trace(args.source, args.query)
        else:
            doc = load_jsonl(args.source, args.trace)
    except urllib.error.HTTPError as e:
        print(f"error: {e.code} from {args.source}: "
              f"{e.read().decode(errors='replace')[:200]}", file=sys.stderr)
        return 1 if e.code == 404 else 2
    except Exception as e:  # noqa: BLE001 - source unreadable is the signal
        print(f"error: cannot load {args.source}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    if doc is None:
        print("error: trace not found", file=sys.stderr)
        return 1
    if "_ids" in doc:
        print("multiple traces in file; pick one with --trace:")
        for tid in doc["_ids"]:
            print(f"  {tid}")
        return 1
    print(render_waterfall(doc, width=args.width))
    return 0


if __name__ == "__main__":
    sys.exit(main())
